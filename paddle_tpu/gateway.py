"""Serving gateway: the front door for a fleet of serving-engine replicas.

PRs 1-7 built everything *behind* the socket — ragged/paged/speculative
engines, AOT-warmed compile caches, telemetry, a live ops endpoint — but
``add_request`` has no deadline, no cancel, no backpressure, and nothing
routes across more than one engine.  :class:`ServingGateway` is that
missing subsystem: it fronts N engine replicas (any mix of the five engine
classes in ``paddle_tpu.serving``) and turns a fast engine into a service
that stays fast under overload, replica stalls, and rolling restarts.

Four disciplines, each host-side only (no compiled program changes):

**Admission control & load shedding.**  Requests wait in bounded
per-priority queues (priority 0 is served first).  Each priority bounds
both queue DEPTH (``max_queue_depth``) and queued TOKEN budget
(``max_queued_tokens`` — prompt + ``max_new_tokens`` per request, the
token-budget-aware limit: a queue of 8 huge prompts is as overloaded as a
queue of 800 small ones).  Past either limit ``submit()`` rejects
IMMEDIATELY with a structured :class:`Overloaded` result — the client gets
a retryable signal in O(1) instead of a admission that silently grows
everyone's tail latency.

**Deadlines & cancellation.**  ``submit(..., ttft_deadline_s=,
deadline_s=)`` bounds time-to-first-token and total latency.  The dispatch
loop expires overdue QUEUED requests before they ever touch an engine, and
cancels overdue IN-FLIGHT ones through the ``Engine.cancel(rid)``
primitive (slots / KV blocks / prefix pins / sampling rows all released;
serving.py).  Expired requests carry a structured
:class:`DeadlineExceeded`; streaming consumers get the terminal
``on_token(gid, None, True)`` end-of-stream either way.
``gateway.cancel(gid)`` is the client-initiated form of the same path.

**Replica routing.**  Default policy is least-outstanding-tokens (the
replica with the smallest Σ of prompt + remaining-budget tokens in
flight).  Replicas with a warm prefix cache get an AFFINITY override:
requests whose prompt chain-digest prefix matches cached blocks route to
that replica (deepest match wins; ties fall back to least-outstanding) —
shared system prompts keep hitting the replica that already holds their
k/v.  Health is watched per the PR 7 ``/healthz`` stall logic: a replica
whose tracer's newest event is older than ``stall_threshold_s`` while it
holds in-flight work is QUARANTINED — its completed requests are
harvested, and every other in-flight request is re-admitted elsewhere
after the documented replay signal ``on_token(gid, None, False)``
(discard the streamed prefix; the rerun re-delivers from token one).

**Graceful drain.**  ``drain(name)`` stops admission to a replica while
its in-flight requests run to completion (zero drops); optionally a
``replacement`` engine is AOT-``warmup()``-ed against a ``cache_dir``
(PR 6) while the old replica drains, and takes traffic the moment the
drain completes — the rolling-restart primitive.

**Resilience** (opt-in via ``resilience=ResiliencePolicy(...)``) — the
failure-response layer above quarantine (docs/RESILIENCE.md): per-replica
CIRCUIT BREAKERS (closed → open on consecutive dispatch failures /
stall-timeouts, half-open probe after ``breaker_open_s``, operator-visible
state), bounded RETRY of :class:`~paddle_tpu.faults
.TransientDispatchError` dispatches with exponential backoff + seeded
jitter and a per-request retry budget (exhaustion is a structured
:class:`RetriesExhausted`, never a silent drop), HEDGED dispatch for
requests whose TTFT deadline is at risk (a second attempt races on
another replica; the first token decides the winner and the loser is
``Engine.cancel``-ed — the consumer stream is single-sourced by
construction), and a BROWNOUT degradation ladder driven by
occupancy/SLO burn (``normal`` → clamp ``max_new_tokens`` →
priority-0-only admission → shed-all; every rung a structured,
observable state with dwell hysteresis, docs/RESILIENCE.md runbook).
With ``resilience=None`` (default) none of these paths run — engine
lowerings and program-cache keys are identical either way (host-side
control flow only).

**Disaggregated prefill/decode + tiered KV migration** (docs/
KV_TIERING.md) — replicas register with a ``role``: ``prefill``
replicas only run gateway-internal prompt prefills whose KV pages are
exported (``engine.export_prefix_pages``) and migrated under a
``migration_bytes_per_tick`` budget into a ``decode`` replica's
:class:`~paddle_tpu.kv_store.TieredKVStore`; the request then
dispatches there and admission restores the pages device-side.  The
prefix-affinity router reads the engines' PUBLIC tier-aware
``prefix_match`` API (a deep DRAM hit outranks a shallow HBM hit), and
``gateway.prefix_index()`` aggregates the fleet-wide index.  Every
pipeline failure — quarantine, stall, meta mismatch, lost destination —
falls back to plain recompute dispatch: slower, never wrong, zero
drops.

The gateway is COOPERATIVE and single-threaded, like the engines it
fronts: ``step()`` runs one round (health → brownout → expiry → drains →
dispatch → hedging → replica steps → harvest → in-flight deadlines), and
``run_to_completion`` drives it.  A replica whose ``step()`` RAISES
mid-tick is quarantined and its in-flight work replayed — one broken
engine never poisons the whole gateway tick.  With a ``tracer=`` it emits ``gateway`` events
(shed/expired/dispatch/reroute/quarantine/drain) through the PR 2 Tracer
— ring buffer, ``summary()``, Prometheus, and chrome exports included —
and ``ops_server.OpsServer.attach(gateway)`` serves the live
``/gateway`` view.

Typical use::

    gw = ServingGateway(tracer=Tracer())
    gw.add_replica(engine_a, "a")
    gw.add_replica(engine_b, "b")
    req = gw.submit([12, 71, 9], max_new_tokens=32, ttft_deadline_s=0.5)
    if req.status == "shed":
        ...                         # req.error is a structured Overloaded
    while gw.pending():
        gw.step()
    assert req.status == "finished" and req.tokens

No reference counterpart: the reference snapshot serves static batches
with no service layer at all (SURVEY §2.3); this is the serving-system
capstone over the beyond-reference engines.
"""

from __future__ import annotations

import collections
import itertools
import logging
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .faults import TransientDispatchError
from .utils.stats import (DEFAULT_TIME_BUCKETS, StatRegistry,
                          prometheus_text as _prometheus_text)

__all__ = ["ServingGateway", "GatewayRequest", "Replica", "Overloaded",
           "DeadlineExceeded", "ResiliencePolicy", "CircuitBreaker",
           "RetriesExhausted", "Brownout", "BROWNOUT_LEVELS", "ROLES"]

#: replica lifecycle states
ACTIVE = "active"
DRAINING = "draining"
QUARANTINED = "quarantined"
STOPPED = "stopped"

#: replica roles (disaggregated prefill/decode serving — docs/KV_TIERING.md).
#: ``unified`` replicas serve whole requests (the pre-disaggregation
#: behaviour); ``prefill`` replicas ONLY run gateway-internal prompt
#: prefills whose KV pages are then migrated out; ``decode`` replicas
#: serve requests and receive migrated pages through their kv_store.
ROLES = ("unified", "prefill", "decode")

#: gateway-request terminal states (plus the live "queued"/"dispatched")
_TERMINAL = frozenset({"finished", "shed", "expired", "cancelled",
                       "failed"})


class Overloaded:
    """Structured shed rejection: the queue the request would have joined
    was over its depth or token budget.  Returned on ``GatewayRequest
    .error`` with ``status == "shed"`` — never an exception, never a
    silent drop: the client sees exactly which limit fired and how deep
    the queue was, the retryable-backpressure contract."""

    __slots__ = ("priority", "queue_depth", "queued_tokens", "est_tokens",
                 "max_queue_depth", "max_queued_tokens")

    def __init__(self, priority, queue_depth, queued_tokens, est_tokens,
                 max_queue_depth, max_queued_tokens):
        self.priority = priority
        self.queue_depth = queue_depth
        self.queued_tokens = queued_tokens
        self.est_tokens = est_tokens
        self.max_queue_depth = max_queue_depth
        self.max_queued_tokens = max_queued_tokens

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return (f"Overloaded(priority={self.priority}, "
                f"queue_depth={self.queue_depth}/{self.max_queue_depth}, "
                f"queued_tokens={self.queued_tokens}"
                f"{'' if self.max_queued_tokens is None else '/' + str(self.max_queued_tokens)})")


class DeadlineExceeded:
    """Structured deadline expiry: ``kind`` is ``"ttft"`` (no first token
    by ``ttft_deadline_s``) or ``"total"`` (``deadline_s`` elapsed).
    ``tokens_delivered`` counts what the consumer already streamed —
    a mid-decode total-deadline cancel keeps the partial output on
    ``GatewayRequest.tokens``."""

    __slots__ = ("kind", "deadline_s", "waited_s", "tokens_delivered")

    def __init__(self, kind, deadline_s, waited_s, tokens_delivered):
        self.kind = kind
        self.deadline_s = deadline_s
        self.waited_s = waited_s
        self.tokens_delivered = tokens_delivered

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return (f"DeadlineExceeded(kind={self.kind!r}, "
                f"deadline_s={self.deadline_s}, "
                f"waited_s={round(self.waited_s, 4)}, "
                f"tokens_delivered={self.tokens_delivered})")


class RetriesExhausted:
    """Structured terminal failure: every retry of a transiently failing
    dispatch was spent.  ``attempts`` counts dispatch attempts made (the
    first try plus ``budget`` retries), ``last_error`` is the repr of
    the final :class:`~paddle_tpu.faults.TransientDispatchError`.  Lands
    on ``GatewayRequest.error`` with ``status == "failed"`` — bounded
    retry never becomes an unbounded silent loop."""

    __slots__ = ("attempts", "budget", "last_error")

    def __init__(self, attempts, budget, last_error):
        self.attempts = attempts
        self.budget = budget
        self.last_error = last_error

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return (f"RetriesExhausted(attempts={self.attempts}, "
                f"budget={self.budget}, last_error={self.last_error!r})")


class Brownout:
    """Structured brownout rejection: the degradation ladder is at a
    rung that does not admit this request (``priority_only`` admits only
    priority 0; ``shed_all`` admits nothing).  Like :class:`Overloaded`
    it is a retryable-backpressure signal, but it names the LADDER state
    — the client can distinguish "queue full" from "service degraded"."""

    __slots__ = ("level", "label", "priority")

    def __init__(self, level, label, priority):
        self.level = level
        self.label = label
        self.priority = priority

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return (f"Brownout(level={self.level}, label={self.label!r}, "
                f"priority={self.priority})")


#: brownout ladder rungs, lowest (healthy) first — the gauge encoding
BROWNOUT_LEVELS = ("normal", "clamp", "priority_only", "shed_all")


class CircuitBreaker:
    """Per-replica dispatch circuit breaker (docs/RESILIENCE.md state
    machine).  CLOSED counts consecutive failures; ``failures_to_open``
    of them OPEN the breaker — the replica leaves the routing set.
    After ``open_s`` the next routing inquiry moves it to HALF_OPEN,
    which admits exactly ONE probe dispatch: a success CLOSES the
    breaker, a failure re-OPENS it (and re-arms the window).  Pure host
    state on the gateway's injectable clock — deterministic under the
    simulation harness."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    __slots__ = ("failures_to_open", "open_s", "state",
                 "consecutive_failures", "opened_at", "_probe_inflight",
                 "probe_gid")

    def __init__(self, failures_to_open: int = 3, open_s: float = 5.0):
        if int(failures_to_open) < 1:
            raise ValueError("failures_to_open must be >= 1")
        if float(open_s) <= 0:
            raise ValueError("open_s must be > 0")
        self.failures_to_open = int(failures_to_open)
        self.open_s = float(open_s)
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.opened_at: Optional[float] = None
        self._probe_inflight = False
        #: gid of the request holding the HALF_OPEN probe claim — the
        #: probe's verdict (success/failure/release) is keyed to THIS
        #: request, so an unrelated pre-open in-flight request
        #: terminating cannot free or fail a probe it never held
        self.probe_gid: Optional[int] = None

    def allow(self, now: float) -> bool:
        """May a dispatch be routed here at ``now``?  Advances OPEN →
        HALF_OPEN once the window has elapsed; HALF_OPEN admits one
        probe at a time (``note_dispatch`` claims it)."""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN:
            if now - (self.opened_at or 0.0) < self.open_s:
                return False
            self.state = self.HALF_OPEN
            self._probe_inflight = False
        return not self._probe_inflight

    def note_dispatch(self, now: float, gid: Optional[int] = None):
        """A dispatch was actually sent (the HALF_OPEN probe claim)."""
        if self.state == self.HALF_OPEN:
            self._probe_inflight = True
            self.probe_gid = gid

    def effectively_open(self, now: float) -> bool:
        """OPEN *and* still inside the window at ``now`` — the
        non-mutating form of what ``allow`` would answer.  An OPEN
        breaker whose window has elapsed is one routing inquiry away
        from HALF_OPEN, so it is not missing capacity: consumers that
        never route (an idle fleet, the autoscaler's signal scan) must
        not treat it as open forever."""
        return (self.state == self.OPEN
                and now - (self.opened_at or 0.0) < self.open_s)

    def record_failure(self, now: float) -> bool:
        """One dispatch failure / stall-timeout; True when this one
        OPENED (or re-opened) the breaker."""
        self.consecutive_failures += 1
        if self.state == self.HALF_OPEN or (
                self.state == self.CLOSED
                and self.consecutive_failures >= self.failures_to_open):
            self.state = self.OPEN
            self.opened_at = now
            self._probe_inflight = False
            self.probe_gid = None
            return True
        if self.state == self.OPEN:
            self.opened_at = now          # still failing: re-arm window
        return False

    def release_probe(self):
        """The HALF_OPEN probe ended without a verdict (client cancel
        before any token): free the claim so the next dispatch can
        probe — neither a success nor a failure."""
        self._probe_inflight = False
        self.probe_gid = None

    def record_success(self) -> bool:
        """A dispatch delivered (first token or finish); True when this
        CLOSED a non-closed breaker."""
        self.consecutive_failures = 0
        self._probe_inflight = False
        self.probe_gid = None
        if self.state != self.CLOSED:
            self.state = self.CLOSED
            self.opened_at = None
            return True
        return False

    def to_dict(self) -> Dict[str, Any]:
        return {"state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "opened_at": self.opened_at,
                "failures_to_open": self.failures_to_open,
                "open_s": self.open_s}

    def __repr__(self):
        return (f"CircuitBreaker({self.state}, "
                f"failures={self.consecutive_failures}/"
                f"{self.failures_to_open})")


class ResiliencePolicy:
    """Every resilience knob, explicit (docs/RESILIENCE.md semantics):

    - **retry**: ``retry_budget`` retries per request beyond the first
      attempt; backoff ``min(retry_backoff_max_s, retry_backoff_s *
      2**(attempt-1))`` scaled by a seeded jitter in ``[1 - retry_jitter,
      1 + retry_jitter]`` — the EQuARX discipline applied to retries: the
      added load is BOUNDED and documented, never an open loop.
    - **breaker**: ``breaker_failures`` consecutive failures open a
      replica's breaker for ``breaker_open_s`` (half-open probe after).
    - **hedge**: with ``hedge=True``, a dispatched request that has no
      first token by ``hedge_ttft_frac`` of its ``ttft_deadline_s`` gets
      ONE hedged attempt on another replica, bounded fleet-wide by
      ``max_hedges`` concurrent hedges (the hedge budget: worst-case
      extra work is ``max_hedges`` duplicate decodes, never 2× traffic).
    - **brownout**: occupancy ((in-flight + queued) / active slots)
      above ``brownout_high`` — or, with ``brownout_use_slo``, any
      firing SLO — climbs the ladder one rung per ``brownout_up_dwell_s``
      of sustained pressure; occupancy below ``brownout_low`` descends
      one rung per ``brownout_down_dwell_s``.  The band between the two
      thresholds holds the current rung (no flapping).  Rung 1+ clamps
      dispatched ``max_new_tokens`` to ``brownout_clamp``."""

    __slots__ = ("retry_budget", "retry_backoff_s", "retry_backoff_max_s",
                 "retry_jitter", "seed", "breaker_failures",
                 "breaker_open_s", "hedge", "hedge_ttft_frac",
                 "max_hedges", "brownout", "brownout_high", "brownout_low",
                 "brownout_up_dwell_s", "brownout_down_dwell_s",
                 "brownout_clamp", "brownout_use_slo")

    def __init__(self, *, retry_budget: int = 2,
                 retry_backoff_s: float = 0.05,
                 retry_backoff_max_s: float = 2.0,
                 retry_jitter: float = 0.5, seed: int = 0,
                 breaker_failures: int = 3, breaker_open_s: float = 5.0,
                 hedge: bool = True, hedge_ttft_frac: float = 0.5,
                 max_hedges: int = 4, brownout: bool = True,
                 brownout_high: float = 2.0, brownout_low: float = 0.75,
                 brownout_up_dwell_s: float = 0.0,
                 brownout_down_dwell_s: float = 5.0,
                 brownout_clamp: int = 16,
                 brownout_use_slo: bool = True):
        if int(retry_budget) < 0:
            raise ValueError("retry_budget must be >= 0")
        if float(retry_backoff_s) < 0 or float(retry_backoff_max_s) < 0:
            raise ValueError("backoff times must be >= 0")
        if not 0.0 <= float(retry_jitter) < 1.0:
            raise ValueError("retry_jitter must be in [0, 1)")
        if not 0.0 < float(hedge_ttft_frac) <= 1.0:
            raise ValueError("hedge_ttft_frac must be in (0, 1]")
        if int(max_hedges) < 0:
            raise ValueError("max_hedges must be >= 0")
        if float(brownout_low) >= float(brownout_high):
            raise ValueError("need brownout_low < brownout_high (the "
                             "hysteresis band)")
        if int(brownout_clamp) < 1:
            raise ValueError("brownout_clamp must be >= 1")
        self.retry_budget = int(retry_budget)
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_backoff_max_s = float(retry_backoff_max_s)
        self.retry_jitter = float(retry_jitter)
        self.seed = int(seed)
        self.breaker_failures = int(breaker_failures)
        self.breaker_open_s = float(breaker_open_s)
        self.hedge = bool(hedge)
        self.hedge_ttft_frac = float(hedge_ttft_frac)
        self.max_hedges = int(max_hedges)
        self.brownout = bool(brownout)
        self.brownout_high = float(brownout_high)
        self.brownout_low = float(brownout_low)
        self.brownout_up_dwell_s = float(brownout_up_dwell_s)
        self.brownout_down_dwell_s = float(brownout_down_dwell_s)
        self.brownout_clamp = int(brownout_clamp)
        self.brownout_use_slo = bool(brownout_use_slo)

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry ``attempt`` (1-based): exponential,
        capped, jittered from the gateway's seeded RNG."""
        base = min(self.retry_backoff_max_s,
                   self.retry_backoff_s * (2.0 ** max(attempt - 1, 0)))
        if self.retry_jitter == 0.0:
            return base
        return base * (1.0 + self.retry_jitter * (2.0 * rng.random() - 1.0))

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return (f"ResiliencePolicy(retries={self.retry_budget}, "
                f"breaker={self.breaker_failures}/{self.breaker_open_s}s, "
                f"hedge={self.hedge}, brownout={self.brownout})")


class _BrownoutLadder:
    """The brownout state machine: one rung at a time, dwell-gated both
    ways, with the ``[low, high]`` hysteresis band holding the current
    rung (the telemetry_slo resolve-band discipline — pressure hovering
    at a threshold cannot flap the ladder)."""

    def __init__(self, policy: ResiliencePolicy):
        self.policy = policy
        self.level = 0
        self.changed_at: Optional[float] = None
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None

    def evaluate(self, now: float, pressure: float,
                 slo_firing: bool) -> int:
        """Advance the ladder; returns +1 / -1 on a rung change this
        round, else 0."""
        p = self.policy
        if pressure >= p.brownout_high or slo_firing:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if self.level < len(BROWNOUT_LEVELS) - 1 \
                    and now - self._above_since >= p.brownout_up_dwell_s:
                self.level += 1
                self.changed_at = now
                self._above_since = now      # next rung needs its own dwell
                return +1
        elif pressure <= p.brownout_low:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if self.level > 0 \
                    and now - self._below_since >= p.brownout_down_dwell_s:
                self.level -= 1
                self.changed_at = now
                self._below_since = now
                return -1
        else:
            # inside the hysteresis band: hold the rung, reset dwells
            self._above_since = None
            self._below_since = None
        return 0

    def to_dict(self) -> Dict[str, Any]:
        return {"level": self.level, "label": BROWNOUT_LEVELS[self.level],
                "changed_at": self.changed_at}


class GatewayRequest:
    """One gateway-tracked request (host-side handle).  ``status`` walks
    ``queued`` → ``dispatched`` → ``finished``, or terminates early as
    ``shed`` / ``expired`` / ``cancelled`` / ``failed`` with the
    structured reason on ``error``.  Timestamps are the gateway's clock
    (injectable for tests)."""

    __slots__ = ("gid", "prompt", "max_new_tokens", "priority",
                 "ttft_deadline_s", "deadline_s", "sampling", "on_token",
                 "status", "tokens", "error", "replica", "engine_rid",
                 "submitted_at", "dispatched_at", "first_token_at",
                 "finished_at", "replays", "trace", "_rerouting",
                 "_pending_expiry", "retries", "not_before", "hedged",
                 "hedge_replica", "hedge_rid", "dispatch_max_new",
                 "no_disagg")

    def __init__(self, gid, prompt, max_new_tokens, priority,
                 ttft_deadline_s, deadline_s, sampling, on_token,
                 submitted_at):
        self.gid = gid
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.ttft_deadline_s = ttft_deadline_s
        self.deadline_s = deadline_s
        self.sampling = dict(sampling)
        self.on_token = on_token
        self.status = "queued"
        self.tokens: List[int] = []
        self.error = None
        self.replica: Optional[str] = None
        self.engine_rid: Optional[int] = None
        self.submitted_at = submitted_at
        self.dispatched_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.replays = 0
        # end-to-end trace identity (telemetry.TraceContext): the ROOT
        # span, minted at submit when the gateway traces; each dispatch
        # mints a child for that engine attempt
        self.trace = None
        self._rerouting = False
        self._pending_expiry: Optional[DeadlineExceeded] = None
        # resilience bookkeeping (all inert when resilience is off):
        # dispatch retries spent, earliest next dispatch (backoff),
        # hedge-attempt identity (replica name + engine rid of the
        # SECOND in-flight attempt, None once resolved), and the
        # possibly-brownout-clamped budget the live attempt was
        # dispatched with
        self.retries = 0
        self.not_before: Optional[float] = None
        self.hedged = False
        self.hedge_replica: Optional[str] = None
        self.hedge_rid: Optional[int] = None
        self.dispatch_max_new: Optional[int] = None
        # a disaggregated-pipeline fallback sets this: the request is
        # served the normal recompute way and never re-enters the
        # pipeline (one fallback would otherwise loop forever)
        self.no_disagg = False

    @property
    def done(self) -> bool:
        return self.status in _TERMINAL

    @property
    def est_tokens(self) -> int:
        """Queue-budget estimate: prompt plus full generation budget."""
        return len(self.prompt) + self.max_new_tokens

    def remaining_tokens(self) -> int:
        """Outstanding-work estimate for routing: whatever of the
        prompt+budget has not been delivered yet."""
        return max(self.est_tokens - len(self.tokens), 0)

    def to_dict(self) -> Dict[str, Any]:
        err = self.error
        return {"gid": self.gid, "status": self.status,
                "priority": self.priority, "replica": self.replica,
                "prompt_len": len(self.prompt),
                "max_new_tokens": self.max_new_tokens,
                "tokens": len(self.tokens), "replays": self.replays,
                "retries": self.retries, "hedged": self.hedged,
                "trace_id": (None if self.trace is None
                             else self.trace.trace_id),
                "error": (err.to_dict() if hasattr(err, "to_dict")
                          else err)}

    def __repr__(self):
        return (f"GatewayRequest(gid={self.gid}, status={self.status!r}, "
                f"replica={self.replica!r}, tokens={len(self.tokens)})")


def _engine_slots(engine) -> int:
    """Slot capacity of one engine — the serving engines expose ``S``
    (max_slots); anything else counts as one slot.  Shared with the
    autoscaler's occupancy signal (one definition of "a slot")."""
    for attr in ("S", "max_slots"):
        v = getattr(engine, attr, None)
        if isinstance(v, int) and v > 0:
            return v
    return 1


class Replica:
    """One engine replica under gateway management: lifecycle state plus
    the gateway's view of its in-flight work (engine rid → request)."""

    def __init__(self, name: str, engine, role: str = "unified"):
        self.name = name
        self.engine = engine
        self.role = role
        self.state = ACTIVE
        self.inflight: Dict[int, GatewayRequest] = {}
        self.reason: Optional[str] = None          # quarantine reason
        self.replacement = None                    # (engine, name) draining
        self.warm_report = None

    def outstanding_tokens(self) -> int:
        return sum(r.remaining_tokens() for r in self.inflight.values())

    def slots_available(self) -> int:
        """Admission headroom: free engine slots not already spoken for by
        the engine's own internal queue (the gateway keeps waiting
        requests in ITS queues, where deadlines and shedding apply)."""
        eng = self.engine
        return len(eng._free_slots()) - len(eng._queue)

    def idle(self) -> bool:
        return not self.inflight and not self.engine.pending()

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "state": self.state,
                "role": self.role,
                "inflight": len(self.inflight),
                "outstanding_tokens": self.outstanding_tokens(),
                "engine": type(self.engine).__name__,
                "reason": self.reason}


class _DisaggJob:
    """One request's disaggregated prefill→decode pipeline state
    (docs/KV_TIERING.md): the prompt runs on a ``prefill``-role replica
    (``max_new_tokens=1`` — the ragged pack's admission prefill IS the
    work; the sampled token is discarded, the decode replica re-derives
    it from the migrated pages), its KV pages are exported and migrated
    under a byte budget into a decode replica's
    :class:`~paddle_tpu.kv_store.TieredKVStore`, and the request is then
    dispatched there — admission restores the pages device-side, so the
    decode replica computes only the bucket's last block.  Every failure
    along the way (quarantine, stall, meta mismatch, dry destination)
    FALLS BACK to plain recompute dispatch: slower, never wrong, zero
    drops."""

    __slots__ = ("req", "src", "prefill_rid", "phase", "phase_at",
                 "prefill_done", "prefill_failed", "migration", "dest",
                 "pages")

    def __init__(self, req: GatewayRequest, src: str, now: float):
        self.req = req
        self.src = src                     # prefill replica name
        self.prefill_rid: Optional[int] = None
        self.phase = "prefill"             # -> migrate -> handoff
        self.phase_at = now
        self.prefill_done = False
        self.prefill_failed = False
        self.migration = None              # kv_store.PageMigration
        self.dest: Optional[str] = None    # decode replica name
        self.pages = None

    def to_dict(self) -> Dict[str, Any]:
        return {"gid": self.req.gid, "phase": self.phase,
                "src": self.src, "dest": self.dest,
                "migration": (None if self.migration is None
                              else self.migration.to_dict())}


class ServingGateway:
    """Multi-replica serving front door (module docstring).

    ``max_queue_depth`` / ``max_queued_tokens``: per-priority admission
    bounds (None disables the token budget).  ``priorities``: number of
    priority classes (0 = highest, dispatched first).
    ``stall_threshold_s``: the PR 7 ``/healthz`` dial — a replica whose
    tracer shows no event for this long while holding in-flight work is
    quarantined.  ``tracer``: optional ``telemetry.Tracer`` for structured
    ``gateway`` events (None keeps every emit behind one attribute
    check).  ``clock``: monotonic-seconds callable — injectable so tests
    drive deadlines deterministically."""

    def __init__(self, replicas=None, *, max_queue_depth: int = 64,
                 max_queued_tokens: Optional[int] = None,
                 priorities: int = 2, stall_threshold_s: float = 30.0,
                 tracer=None, clock: Callable[[], float] = time.monotonic,
                 request_history: int = 4096,
                 resilience: Optional[ResiliencePolicy] = None,
                 migration_bytes_per_tick: Optional[int] = 8 << 20,
                 logger: Optional[logging.Logger] = None):
        if int(priorities) < 1:
            raise ValueError("priorities must be >= 1")
        if int(max_queue_depth) < 1:
            raise ValueError("max_queue_depth must be >= 1")
        self.max_queue_depth = int(max_queue_depth)
        self.max_queued_tokens = (None if max_queued_tokens is None
                                  else int(max_queued_tokens))
        self.priorities = int(priorities)
        self.stall_threshold_s = float(stall_threshold_s)
        self.tracer = tracer
        self._clock = clock
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._queues: List[collections.deque] = [
            collections.deque() for _ in range(self.priorities)]
        self._queued_tokens = [0] * self.priorities
        self._replicas: Dict[str, Replica] = {}
        # gid → handle while live, plus a BOUNDED tail of terminal
        # handles for late cancel()/request() lookups — a long-lived
        # gateway must not grow host memory per request served (the
        # caller's own handle from submit() stays valid regardless)
        self.request_history = int(request_history)
        # optional SLO monitor (telemetry_slo.SLOMonitor): gateway-level
        # TTFT samples and terminal counts forward into its windowed
        # stores behind one attribute check
        self._slo = None
        # optional engine factory (autoscaler scale-out spawns from it);
        # registered via register_replica_factory
        self._replica_factory: Optional[Callable[[], Any]] = None
        self._requests: Dict[int, GatewayRequest] = {}
        self._terminal_order: collections.deque = collections.deque()
        self._finished: Dict[int, List[int]] = {}
        self._gids = itertools.count()
        # disaggregated prefill/decode pipeline (docs/KV_TIERING.md):
        # gid -> _DisaggJob while a request's pages are being produced /
        # migrated; the byte budget paces each migration per step()
        if migration_bytes_per_tick is not None \
                and int(migration_bytes_per_tick) < 1:
            raise ValueError("migration_bytes_per_tick must be >= 1 "
                             "(or None for unbounded)")
        self.migration_bytes_per_tick = (
            None if migration_bytes_per_tick is None
            else int(migration_bytes_per_tick))
        self._disagg: Dict[int, _DisaggJob] = {}
        # _disagg is read by ops-server scrape threads (GET /kvstore /
        # /gateway) while step() inserts/pops jobs — every mutation and
        # every iteration-snapshot goes through this lock (the PR 12
        # SLOMonitor._firing discipline)
        self._disagg_lock = threading.Lock()
        # per-tick prefix-match memo (gid, replica) -> match: the
        # disagg coverage gate and _route's affinity scoring both walk
        # the chain digests for the same request in the same tick —
        # ONE walk per (request, replica) per step(), cleared each round
        self._match_memo: Dict[Tuple[int, str], Dict[str, Any]] = {}
        self._kvstats = StatRegistry()
        self._stats = StatRegistry()
        self._stats.histogram("queue_seconds", DEFAULT_TIME_BUCKETS)
        self._stats.histogram("ttft_seconds", DEFAULT_TIME_BUCKETS)
        # resilience layer (None = every resilience path is one attribute
        # check and the pre-resilience control flow byte-for-byte)
        self.resilience = resilience
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._brownout: Optional[_BrownoutLadder] = None
        self._hedges_live = 0
        self._rstats = StatRegistry()
        self._rrng = random.Random(0 if resilience is None
                                   else resilience.seed)
        if resilience is not None and resilience.brownout:
            self._brownout = _BrownoutLadder(resilience)
        for engine in (replicas or []):
            self.add_replica(engine)

    # ------------------------------------------------------------ fleet --

    def add_replica(self, engine, name: Optional[str] = None,
                    role: str = "unified") -> str:
        """Register an engine replica (any of the five serving classes —
        it only needs the shared scheduling surface: ``add_request`` /
        ``step`` / ``pop_finished`` / ``cancel`` / ``pending``).

        ``role`` (docs/KV_TIERING.md): ``"unified"`` (default) serves
        whole requests; ``"prefill"`` only runs gateway-internal prompt
        prefills whose KV pages migrate out (it is excluded from request
        routing); ``"decode"`` serves requests and receives migrated
        pages — it needs a :class:`~paddle_tpu.kv_store.TieredKVStore`
        (one is auto-attached when the engine supports
        ``attach_kv_store`` and has none).  Both disaggregated roles
        need a prefix-caching engine: pages are addressed by its chain
        digests."""
        if not hasattr(engine, "cancel"):
            raise TypeError(
                f"{type(engine).__name__} has no cancel(rid) — the gateway "
                f"needs the serving-engine cancellation primitive")
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r}; want one of "
                             f"{ROLES}")
        if role != "unified" and not getattr(engine, "prefix_caching",
                                             False):
            raise ValueError(
                f"role {role!r} needs a prefix-caching engine "
                f"(enable_prefix_cache=True): KV pages are addressed by "
                f"prefix-cache chain digests")
        if role == "decode" and getattr(engine, "kv_store", None) is None:
            attach = getattr(engine, "attach_kv_store", None)
            if attach is None:
                raise ValueError(
                    f"role 'decode' needs an engine with a kv_store "
                    f"(TieredKVStore) to receive migrated pages; "
                    f"{type(engine).__name__} supports neither")
            from .kv_store import TieredKVStore
            attach(TieredKVStore(tracer=self.tracer))
        if name is None:
            i = len(self._replicas)
            while f"r{i}" in self._replicas:     # auto-names never collide
                i += 1
            name = f"r{i}"
        if name in self._replicas and \
                self._replicas[name].state != STOPPED:
            raise ValueError(f"replica {name!r} already registered")
        self._replicas[name] = Replica(name, engine, role=role)
        if self.resilience is not None:
            self._breakers[name] = CircuitBreaker(
                self.resilience.breaker_failures,
                self.resilience.breaker_open_s)
        self._stats.add("replicas_added")
        return name

    def remove_replica(self, name: str) -> Replica:
        """Deregister a STOPPED replica — the final step of an elastic
        scale-down (``drain`` without replacement leaves the stopped
        shell registered so ``is_drained`` stays answerable; a long-lived
        elastic fleet must not accumulate one dead entry per drain).
        Only stopped replicas may be removed: draining ones still hold
        work, and removing an active one would drop its in-flight
        bookkeeping."""
        rep = self.replica(name)
        if rep.state != STOPPED:
            raise ValueError(f"replica {name!r} is {rep.state}; only "
                             f"stopped replicas can be removed (drain it "
                             f"first)")
        del self._replicas[name]
        self._breakers.pop(name, None)
        self._stats.add("replicas_removed")
        self._emit("removed", replica=name)
        return rep

    def register_replica_factory(self, factory: Optional[Callable[[], Any]]
                                 ) -> Optional[Callable[[], Any]]:
        """Register (or with None clear) the engine factory that elastic
        scale-out spawns replicas from — a zero-arg callable returning a
        FRESH engine (any of the five serving classes).  The gateway never
        calls it itself; ``autoscaler.ElasticAutoscaler`` does, then warms
        and ``add_replica``s the result."""
        if factory is not None and not callable(factory):
            raise TypeError(f"replica factory must be callable, got "
                            f"{factory!r}")
        self._replica_factory = factory
        return factory

    @property
    def replica_factory(self) -> Optional[Callable[[], Any]]:
        return self._replica_factory

    def replica(self, name: str) -> Replica:
        rep = self._replicas.get(name)
        if rep is None:
            raise KeyError(f"unknown replica {name!r}")
        return rep

    def replicas(self) -> List[Replica]:
        """Every registered replica (all lifecycle states) — the public
        fleet enumeration the autoscaler and ops views read."""
        return list(self._replicas.values())

    def replica_tracers(self) -> List[Tuple[str, Any]]:
        """(name, tracer) for every CURRENT replica engine that has one —
        the public enumeration ``ops_server`` pulls per ``/requests`` /
        ``/request/<id>`` query, so drain-swapped replacements feed the
        trace stitcher without re-attaching anything."""
        out = []
        for name, rep in list(self._replicas.items()):
            tr = getattr(rep.engine, "tracer", None)
            if tr is not None:
                out.append((name, tr))
        return out

    def quarantine(self, name: str, reason: str = "manual"):
        """Pull a replica out of rotation: completed requests are
        harvested, every other in-flight request is cancelled on the
        replica (host-side bookkeeping — safe even when the device is
        wedged) and re-admitted at the FRONT of its priority queue after
        the documented replay signal ``on_token(gid, None, False)``."""
        rep = self.replica(name)
        if rep.state in (QUARANTINED, STOPPED):
            return rep
        was_draining = rep.state == DRAINING
        rep.state = QUARANTINED
        rep.reason = reason
        # a quarantine is the stall/timeout form of a dispatch failure:
        # the breaker opens too, so an operator reinstate() is probed
        # (half-open) instead of trusted blindly
        self._breaker_failure(name, self._clock(), reason)
        self._stats.add("quarantines")
        self._emit("quarantine", replica=name, reason=reason,
                   inflight=len(rep.inflight))
        self._log.warning("gateway: quarantined replica %s (%s), "
                          "re-admitting %d in-flight request(s)",
                          name, reason, len(rep.inflight))
        self._reroute_inflight(rep)
        if was_draining:
            # a drain interrupted by quarantine still COMPLETES: the
            # rerouted work finishes elsewhere, and the (possibly already
            # warmed) replacement must not be silently dropped —
            # is_drained() stays answerable and drains_started/_completed
            # stay symmetric
            self._complete_drain(rep)
        return rep

    def reinstate(self, name: str):
        """Return a quarantined replica to rotation (operator decision —
        the gateway never auto-reinstates a replica it benched)."""
        rep = self.replica(name)
        if rep.state == QUARANTINED:
            rep.state = ACTIVE
            rep.reason = None
        return rep

    def drain(self, name: str, replacement=None,
              cache_dir: Optional[str] = None, warm: bool = True,
              replacement_name: Optional[str] = None):
        """Gracefully drain a replica: admission stops NOW, in-flight work
        runs to completion under ``step()``, and once idle the replica is
        STOPPED.  ``replacement``: an engine to take its place — with
        ``warm=True`` it is AOT-``warmup()``-ed immediately (optionally
        against ``cache_dir``, the PR 6 persistent compile cache) so it
        joins the fleet already compiled.  Returns the warmup report (or
        None)."""
        rep = self.replica(name)
        if rep.state == STOPPED:
            return rep.warm_report
        # validate the hand-over NOW, not rounds later inside step() when
        # the drain completes (by then the replacement reference would be
        # cleared and the fleet left a replica short)
        if replacement is not None:
            if not hasattr(replacement, "cancel"):
                raise TypeError(
                    f"{type(replacement).__name__} has no cancel(rid) — "
                    f"the gateway needs the serving-engine cancellation "
                    f"primitive")
            other = self._replicas.get(replacement_name)
            if other is not None and other is not rep \
                    and other.state != STOPPED:
                raise ValueError(
                    f"replacement name {replacement_name!r} is a live "
                    f"replica")
        rep.state = DRAINING
        rep.replacement = (replacement, replacement_name)
        self._stats.add("drains_started")
        self._emit("drain_start", replica=name,
                   inflight=len(rep.inflight),
                   replacement=replacement is not None)
        if replacement is not None and warm:
            try:
                rep.warm_report = replacement.warmup(cache_dir=cache_dir)
            except NotImplementedError as e:
                # TP/mesh engines compile on first dispatch (serving.py);
                # the swap still proceeds, just unwarmed
                self._log.debug("gateway: replacement warmup skipped: %r",
                                e)
        self._advance_drains()
        return rep.warm_report

    def is_drained(self, name: str) -> bool:
        return self.replica(name).state == STOPPED

    # --------------------------------------------------------- admission --

    def submit(self, prompt, max_new_tokens: int, *, priority: int = 0,
               ttft_deadline_s: Optional[float] = None,
               deadline_s: Optional[float] = None, on_token=None,
               **sampling) -> GatewayRequest:
        """Admit (or shed) one request; always returns the
        :class:`GatewayRequest` handle.  A shed request is terminal on
        return: ``status == "shed"`` with a structured
        :class:`Overloaded` on ``error`` — and a streaming consumer gets
        the terminal ``on_token(gid, None, True)`` immediately, so no
        rejection is ever silent."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 0 <= int(priority) < self.priorities:
            raise ValueError(f"priority must be in [0, {self.priorities})")
        now = self._clock()
        req = GatewayRequest(next(self._gids), prompt, max_new_tokens,
                             priority, ttft_deadline_s, deadline_s,
                             sampling, on_token, now)
        if self.tracer is not None:
            # mint the request's end-to-end trace: this root context is
            # THE trace_id every gateway event and (via per-dispatch
            # child spans) every engine-timeline event will carry
            from .telemetry import TraceContext
            req.trace = TraceContext.root()
        self._requests[req.gid] = req
        self._stats.add("submitted")
        if self._slo is not None:
            self._slo.count("submitted")
        self._emit("submit", gid=req.gid, priority=req.priority,
                   prompt_len=len(prompt),
                   max_new_tokens=req.max_new_tokens,
                   **self._trace_fields(req))
        if self._brownout is not None and self._brownout.level >= 2:
            lvl = self._brownout.level
            if lvl >= 3 or req.priority > 0:
                # the ladder's admission rungs: priority_only admits only
                # priority 0, shed_all admits nothing — structured, never
                # silent (same contract as Overloaded)
                req.error = Brownout(lvl, BROWNOUT_LEVELS[lvl],
                                     req.priority)
                self._rstats.add("brownout_sheds")
                self._finalize(req, "shed", now)
                self._emit("shed", gid=req.gid, priority=req.priority,
                           over="brownout", level=lvl,
                           **self._trace_fields(req))
                return req
        q = self._queues[req.priority]
        qtok = self._queued_tokens[req.priority]
        over_depth = len(q) >= self.max_queue_depth
        over_tokens = (self.max_queued_tokens is not None
                       and qtok + req.est_tokens > self.max_queued_tokens)
        if over_depth or over_tokens:
            req.error = Overloaded(req.priority, len(q), qtok,
                                   req.est_tokens, self.max_queue_depth,
                                   self.max_queued_tokens)
            self._finalize(req, "shed", now)
            self._emit("shed", gid=req.gid, priority=req.priority,
                       queue_depth=len(q), queued_tokens=qtok,
                       over=("depth" if over_depth else "tokens"),
                       **self._trace_fields(req))
            return req
        q.append(req)
        self._queued_tokens[req.priority] += req.est_tokens
        return req

    def set_slo(self, slo):
        """Attach (or with None detach) a ``telemetry_slo.SLOMonitor``:
        submitted/terminal counts and gateway-level TTFT samples
        (submit → first surviving token) forward into its windowed
        stores — the inputs of the shed-rate and TTFT objectives."""
        self._slo = slo
        return slo

    @staticmethod
    def _trace_fields(req: GatewayRequest, ctx=None) -> Dict[str, Any]:
        """trace_id/span_id/parent_span_id fields for a request-scoped
        gateway event: the dispatch-attempt child when ``ctx`` is given,
        else the request's root span; {} for untraced requests."""
        if ctx is not None:
            return ctx.to_dict()
        if req.trace is None:
            return {}
        return req.trace.to_dict()

    def cancel(self, gid: int) -> bool:
        """Client-initiated cancellation: a queued request is removed and
        finalized here; a dispatched one rides ``Engine.cancel`` (exact
        resource release, terminal stream signal).  False: unknown or
        already terminal."""
        req = self._requests.get(gid)
        if req is None or req.done:
            return False
        job = self._disagg.get(gid)
        if job is not None:
            # mid-pipeline (prefill/migrate/handoff): tear the job down
            # — the prefill attempt is cancelled, host-side pages are
            # dropped with the plan — and finalize here
            self._drop_job(job)
            self._finalize(req, "cancelled", self._clock())
            self._emit("cancel", gid=gid, where="migration",
                       **self._trace_fields(req))
            return True
        if req.status == "queued":
            self._unqueue(req)
            self._finalize(req, "cancelled", self._clock())
            self._emit("cancel", gid=gid, where="queued",
                       **self._trace_fields(req))
            return True
        rep = self._replicas.get(req.replica)
        if rep is None or req.engine_rid is None:
            return False
        if rep.engine.cancel(req.engine_rid):
            # the engine's terminal on_token already finalized the handle
            self._emit("cancel", gid=gid, where="inflight",
                       replica=rep.name, **self._trace_fields(req))
            return True
        return False

    # -------------------------------------------------------- scheduling --

    def step(self):
        """One gateway round: health-check replicas, advance the brownout
        ladder, expire overdue queued requests, advance drains, dispatch
        to replicas, hedge TTFT-at-risk requests, step every replica with
        work, harvest completions, enforce in-flight deadlines.  A
        replica whose ``step()`` raises is quarantined and replayed —
        the exception never escapes the gateway tick."""
        self._check_health()
        self._match_memo.clear()       # affinity walks memoized per round
        now = self._clock()
        if self._brownout is not None:
            self._evaluate_brownout(now)
        self._expire_queued(now)
        self._advance_drains()
        self._dispatch(now)
        if self.resilience is not None and self.resilience.hedge:
            self._maybe_hedge(self._clock())
        for rep in list(self._replicas.values()):
            if rep.state in (ACTIVE, DRAINING) and rep.engine.pending():
                try:
                    rep.engine.step()
                except Exception as e:  # noqa: BLE001 — isolation: one
                    # raising engine must never poison the whole tick
                    self._on_step_error(rep, e)
        self._harvest()
        if self._disagg:
            # after harvest: a prefill that completed THIS tick exports
            # and starts migrating immediately (overlap with serving)
            self._advance_disagg(self._clock())
        self._enforce_inflight_deadlines(self._clock())
        self._advance_drains()

    def _on_step_error(self, rep: Replica, exc: BaseException):
        """A replica engine raised mid-tick: surface it, open its
        breaker, quarantine it (in-flight work replays elsewhere after
        the documented replay signal) — the other replicas' work in this
        very tick proceeds untouched."""
        self._stats.add("step_errors")
        self._log.warning("gateway: replica %s step() raised: %r — "
                          "quarantining and replaying its in-flight work",
                          rep.name, exc)
        self._emit("replica_step_error", replica=rep.name,
                   error=repr(exc))
        # quarantine() records the breaker failure (the stall/timeout
        # form); no separate count here or one event would tick twice
        self.quarantine(rep.name, reason=f"step raised: {exc!r}")

    def pending(self) -> bool:
        if any(self._queues) or self._disagg:
            return True
        return any(rep.inflight or (rep.state in (ACTIVE, DRAINING)
                                    and rep.engine.pending())
                   for rep in self._replicas.values())

    def run_to_completion(self, max_ticks: Optional[int] = None
                          ) -> Dict[int, List[int]]:
        """Drive ``step()`` until nothing is queued or in flight; returns
        ``pop_finished()``."""
        ticks = 0
        while self.pending():
            self.step()
            ticks += 1
            if max_ticks is not None and ticks > max_ticks:
                raise RuntimeError(f"not done after {max_ticks} ticks")
        return self.pop_finished()

    def pop_finished(self) -> Dict[int, List[int]]:
        """Completed generations since the last pop: {gid: tokens}.  Only
        natural completions land here — shed/expired/cancelled requests
        terminate on their handle (``status`` + ``error``)."""
        out, self._finished = self._finished, {}
        return out

    def request(self, gid: int) -> GatewayRequest:
        req = self._requests.get(gid)
        if req is None:
            raise KeyError(f"unknown gateway request {gid}")
        return req

    # ----------------------------------------------------- step internals --

    def _check_health(self):
        """PR 7 ``/healthz`` stall logic applied per replica: in-flight
        work + a tracer whose newest event is older than the threshold =
        a stalled tick → quarantine.  An idle replica is never flagged
        (no work → no events is healthy), and a replica without a tracer
        is trusted (nothing to judge by)."""
        for rep in list(self._replicas.values()):
            if rep.state not in (ACTIVE, DRAINING) or not rep.inflight:
                continue
            tracer = getattr(rep.engine, "tracer", None)
            if tracer is None:
                continue
            try:
                age = tracer.last_event_age_s()
            except Exception as e:  # noqa: BLE001 — a broken tracer must
                # not take the dispatch loop down with it
                self._log.debug("gateway: health scan failed on %s: %r",
                                rep.name, e)
                continue
            if age is not None and age > self.stall_threshold_s:
                self.quarantine(rep.name,
                                reason=f"stalled tick ({age:.1f}s > "
                                       f"{self.stall_threshold_s:.1f}s)")

    def _expire_queued(self, now: float):
        for pri, q in enumerate(self._queues):
            if not q:
                continue
            keep = collections.deque()
            for req in q:
                waited = now - req.submitted_at
                kind = None
                if req.deadline_s is not None and waited > req.deadline_s:
                    kind = "total"
                elif (req.ttft_deadline_s is not None
                        and waited > req.ttft_deadline_s):
                    kind = "ttft"
                if kind is None:
                    keep.append(req)
                    continue
                self._queued_tokens[pri] -= req.est_tokens
                req.error = DeadlineExceeded(kind, req.deadline_s
                                             if kind == "total"
                                             else req.ttft_deadline_s,
                                             waited, 0)
                self._finalize(req, "expired", now)
                self._stats.add(f"expired_{kind}")
                # field name "deadline", not "kind": "kind" is the ring
                # event's reserved key (Tracer.emit's positional)
                self._emit("expired", gid=req.gid, deadline=kind,
                           waited_s=waited, where="queued",
                           **self._trace_fields(req))
            self._queues[pri] = keep

    def _enforce_inflight_deadlines(self, now: float):
        for rep in self._replicas.values():
            for rid, req in list(rep.inflight.items()):
                if req.done:
                    continue    # hedged twin already finalized this round
                if not (rep.name == req.replica
                        and rid == req.engine_rid):
                    continue    # hedge-attempt entry: enforced via its
                    #             primary (both attempts are cancelled)
                waited = now - req.submitted_at
                kind = None
                if req.deadline_s is not None and waited > req.deadline_s:
                    kind = "total"
                elif (req.first_token_at is None
                        and req.ttft_deadline_s is not None
                        and waited > req.ttft_deadline_s):
                    kind = "ttft"
                if kind is None:
                    continue
                req._pending_expiry = DeadlineExceeded(
                    kind, req.deadline_s if kind == "total"
                    else req.ttft_deadline_s, waited, len(req.tokens))
                self._stats.add(f"expired_{kind}")
                self._emit("expired", gid=req.gid, deadline=kind,
                           waited_s=waited, where="inflight",
                           replica=rep.name,
                           tokens_delivered=len(req.tokens),
                           **self._trace_fields(req))
                self._abort_hedge(req)    # no-op when not hedging
                if not rep.engine.cancel(rid):
                    # lost the race with retirement: the engine finished
                    # it this very round — harvest delivers it, the
                    # deadline miss stays recorded as an event only
                    req._pending_expiry = None

    def _advance_drains(self):
        for rep in list(self._replicas.values()):
            if rep.state == DRAINING and rep.idle():
                self._complete_drain(rep)

    def _complete_drain(self, rep: Replica):
        rep.state = STOPPED
        self._stats.add("drains_completed")
        self._emit("drain_done", replica=rep.name)
        replacement, new_name = rep.replacement or (None, None)
        rep.replacement = None
        if replacement is not None:
            name = self.add_replica(replacement, name=new_name)
            self._emit("replaced", replica=rep.name, by=name)

    def _dispatch(self, now: float):
        """Move queued requests onto replicas, highest priority first,
        FIFO within a priority, while any replica has admission headroom.
        With resilience on, requests inside their retry backoff window
        (``not_before``) are stepped over — they keep their queue
        position but never block the requests behind them."""
        if self.resilience is None:
            for pri, q in enumerate(self._queues):
                while q:
                    req = q[0]
                    prep = self._disagg_route(req, now)
                    if prep is not None \
                            and self._begin_prefill(prep, req, now):
                        q.popleft()
                        self._queued_tokens[pri] -= req.est_tokens
                        continue
                    target = self._route(req, now)
                    if target is None:
                        return          # fleet-wide: no headroom anywhere
                    q.popleft()
                    self._queued_tokens[pri] -= req.est_tokens
                    self._dispatch_to(target, req, now)
            return
        fleet_full = False
        for pri in range(self.priorities):
            if fleet_full:
                break          # _route candidacy is request-independent:
                #                no headroom for one request this tick
                #                means none for any (same early exit as
                #                the non-resilience loop)
            q = self._queues[pri]
            deferred: collections.deque = collections.deque()
            while q:
                req = q.popleft()
                if req.not_before is not None and now < req.not_before:
                    deferred.append(req)      # backing off: hold in place
                    continue
                prep = self._disagg_route(req, now)
                if prep is not None \
                        and self._begin_prefill(prep, req, now):
                    self._queued_tokens[pri] -= req.est_tokens
                    continue
                target = self._route(req, now)
                if target is None:
                    # no headroom anywhere: put everything back, done
                    deferred.append(req)
                    deferred.extend(q)
                    q.clear()
                    fleet_full = True
                    break
                self._queued_tokens[pri] -= req.est_tokens
                if self._dispatch_to(target, req, now) is not None:
                    # transient dispatch failure: the request is backing
                    # off for a retry — hold it in this queue
                    self._queued_tokens[pri] += req.est_tokens
                    deferred.append(req)
            self._queues[pri] = deferred

    def _route(self, req: GatewayRequest, now: float,
               exclude: Optional[str] = None) -> Optional[Replica]:
        """Pick the target replica: among ACTIVE non-``prefill`` replicas
        with admission headroom (and, with resilience on, a breaker that
        allows dispatch), the deepest TIER-AWARE prefix match wins: a
        deep lower-tier hit (restorable from DRAM/disk, no recompute)
        outranks a shallow HBM hit; equal total depth prefers the warmer
        (HBM-deeper) replica; ties — including the common no-match case
        — go to the least outstanding tokens.  ``exclude`` drops one
        name (the hedge path never hedges onto the primary's
        replica)."""
        cands = [rep for rep in self._replicas.values()
                 if rep.state == ACTIVE and rep.role != "prefill"
                 and rep.slots_available() > 0
                 and rep.name != exclude
                 and self._breaker_allows(rep.name, now)]
        if not cands:
            return None
        scored = []
        for i, rep in enumerate(cands):
            m = self._match_of(rep, req)
            scored.append((-m["total"], -m["hbm"],
                           rep.outstanding_tokens(), i))
        return cands[min(scored)[3]]

    def _match_of(self, rep: Replica, req: GatewayRequest
                  ) -> Dict[str, Any]:
        """Memoized tier-aware affinity read for this round (the memo
        clears at every ``step()``): the disagg coverage gate and the
        router score the SAME (request, replica) pairs back to back —
        one chain-digest walk serves both."""
        key = (req.gid, rep.name)
        m = self._match_memo.get(key)
        if m is None:
            m = self._prefix_match(rep.engine, req.prompt)
            self._match_memo[key] = m
        return m

    @staticmethod
    def _prefix_match(engine, prompt: List[int]) -> Dict[str, Any]:
        """Tier-aware affinity read through the engines' PUBLIC
        ``prefix_match`` API (serving.py contract — the router no longer
        reaches into ``engine._prefix_cache``): a pure read, no LRU
        touch, no pinning.  Engines without the API (or with a broken
        one) score zero rather than breaking routing."""
        fn = getattr(engine, "prefix_match", None)
        if fn is None:
            return {"hbm": 0, "total": 0, "tiers": []}
        try:
            return fn(prompt)
        except Exception as e:  # noqa: BLE001 — affinity is advisory;
            # a broken read must not take the dispatch loop down
            logging.getLogger(__name__).debug(
                "gateway: prefix_match failed: %r", e)
            return {"hbm": 0, "total": 0, "tiers": []}

    def _dispatch_to(self, rep: Replica, req: GatewayRequest, now: float
                     ) -> Optional[GatewayRequest]:
        """Dispatch one queued request onto ``rep``.  Returns None when
        the request left the queue (dispatched, or terminally failed);
        returns the request itself when a TRANSIENT failure put it into
        retry backoff and the caller must hold it queued."""
        queue_s = now - req.submitted_at
        # one child span per engine attempt (reroute re-dispatches mint a
        # fresh one): the engine binds its rid to this context, so the
        # attempt's whole timeline carries the shared trace_id
        ctx = req.trace.child() if req.trace is not None else None
        mnt = req.max_new_tokens
        if self._brownout is not None and self._brownout.level >= 1:
            # rung 1+ clamps the generation budget — the service sheds
            # WORK before it sheds REQUESTS
            mnt = min(mnt, self.resilience.brownout_clamp)
        try:
            rid = rep.engine.add_request(
                req.prompt, mnt,
                on_token=self._make_on_token(rep, req), trace_ctx=ctx,
                **req.sampling)
        except TransientDispatchError as e:
            return self._on_transient_dispatch_error(rep, req, now, e)
        except (ValueError, TypeError, NotImplementedError) as e:
            # a structurally unservable request (prompt over max_len,
            # sampling knobs the engine rejects): terminal "failed", the
            # loop keeps running
            req.error = repr(e)
            self._finalize(req, "failed", now)
            self._emit("failed", gid=req.gid, replica=rep.name,
                       error=repr(e), **self._trace_fields(req))
            return None
        self._breaker_note_dispatch(rep.name, now, gid=req.gid)
        req.engine_rid = rid
        req.replica = rep.name
        req.dispatched_at = now
        req.dispatch_max_new = mnt
        req.not_before = None
        req.status = "dispatched"
        rep.inflight[rid] = req
        self._stats.add("dispatched")
        self._stats.observe("queue_seconds", queue_s)
        fields = {}
        if mnt != req.max_new_tokens:
            self._rstats.add("brownout_clamped")
            fields["clamped_max_new"] = mnt
        if req.retries:
            fields["retries"] = req.retries
        self._emit("dispatch", gid=req.gid, replica=rep.name,
                   queue_s=queue_s, priority=req.priority, **fields,
                   **self._trace_fields(req, ctx))
        return None

    def _on_transient_dispatch_error(self, rep: Replica,
                                     req: GatewayRequest, now: float,
                                     exc: TransientDispatchError
                                     ) -> Optional[GatewayRequest]:
        """A retryable dispatch failure: count it on the replica's
        breaker and either schedule a backed-off retry (within the
        per-request budget) or terminate with a structured
        :class:`RetriesExhausted`.  Without a resilience policy the
        failure is terminal immediately (still structured, never
        silent)."""
        self._breaker_failure(rep.name, now, repr(exc))
        if self.resilience is None:
            req.error = repr(exc)
            self._finalize(req, "failed", now)
            self._emit("failed", gid=req.gid, replica=rep.name,
                       error=repr(exc), **self._trace_fields(req))
            return None
        if req.retries >= self.resilience.retry_budget:
            # the first attempt plus every budgeted retry failed:
            # structured terminal, never an unbounded loop
            req.error = RetriesExhausted(req.retries + 1,
                                         self.resilience.retry_budget,
                                         repr(exc))
            self._rstats.add("retries_exhausted")
            self._finalize(req, "failed", now)
            self._remit("retries_exhausted", gid=req.gid,
                        replica=rep.name, attempts=req.retries + 1,
                        error=repr(exc))
            return None
        req.retries += 1
        backoff = self.resilience.backoff_s(req.retries, self._rrng)
        req.not_before = now + backoff
        self._rstats.add("retries")
        self._remit("retry", gid=req.gid, replica=rep.name,
                    attempt=req.retries, backoff_s=round(backoff, 6),
                    error=repr(exc))
        return req

    def _make_on_token(self, rep: Replica, req: GatewayRequest):
        """The engine-facing streaming callback: forwards to the user's
        ``on_token`` under the GATEWAY id, tracks first-token/TTFT, and
        translates the engines' two sentinel signals — replay
        (``None, False``) resets the stream, terminal (``None, True``)
        resolves to expired/cancelled per what triggered the cancel.

        With hedging, a request can have TWO live engine attempts; each
        gets its own closure over the SAME handle.  Every signal is
        identity-checked against the request's current attempt fields
        ((replica, rid) pairs) — a losing/stale attempt's signals only
        clear bookkeeping, so the consumer stream is single-sourced and
        tokens are never double-delivered.  The FIRST token decides the
        hedge winner; the loser is cancelled on its engine right there."""
        def cb(_rid, tok, done):
            primary = (rep.name == req.replica
                       and _rid == req.engine_rid)
            hedge = (rep.name == req.hedge_replica
                     and _rid == req.hedge_rid)
            if req.done or not (primary or hedge):
                # terminal already, or a stale/losing attempt: nothing
                # reaches the consumer; a terminal signal just clears the
                # replica's bookkeeping entry
                if tok is None and done:
                    rep.inflight.pop(_rid, None)
                return
            if tok is None and not done:
                # engine-level preemption replay (paged pool pressure):
                # reset and forward — the rerun re-delivers from token one
                req.tokens = []
                req.first_token_at = None
                req.replays += 1
                if req.on_token is not None:
                    req.on_token(req.gid, None, False)
                return
            if tok is None and done:
                rep.inflight.pop(_rid, None)
                if req._rerouting:
                    return          # quarantine path signals separately
                now = self._clock()
                if req._pending_expiry is not None:
                    req.error = req._pending_expiry
                    req._pending_expiry = None
                    self._finalize(req, "expired", now)      # forwards the
                else:                                        # terminal sig
                    self._finalize(req, "cancelled", now)
                return
            if req.first_token_at is None:
                # TTFT is observed into the histogram at FINISH, not here:
                # a preemption/reroute would roll this attempt back, and
                # the histogram carries one sample per request — the
                # surviving attempt (the Tracer's documented semantics)
                req.first_token_at = self._clock()
                self._breaker_success(rep.name)
                if req.hedge_rid is not None:
                    # the race is decided by THIS token: promote the
                    # winner, cancel the loser
                    self._resolve_hedge(req, winner_is_hedge=hedge)
            req.tokens.append(int(tok))
            if req.on_token is not None:
                req.on_token(req.gid, int(tok), done)
        return cb

    # ----------------------------------------------------------- hedging --

    def _maybe_hedge(self, now: float):
        """Dispatch hedge attempts for TTFT-at-risk requests (module
        docstring): a dispatched request with a TTFT deadline, no first
        token, and ``hedge_ttft_frac`` of its deadline already spent gets
        ONE second attempt on a different replica — first token wins,
        loser is cancelled.  Fleet-wide concurrency is bounded by
        ``max_hedges``."""
        pol = self.resilience
        if self._hedges_live >= pol.max_hedges:
            return
        for rep in list(self._replicas.values()):
            for rid, req in list(rep.inflight.items()):
                if self._hedges_live >= pol.max_hedges:
                    return
                if (req.done or req.hedged
                        or req.ttft_deadline_s is None
                        or req.first_token_at is not None
                        or rep.name != req.replica
                        or rid != req.engine_rid):
                    continue
                waited = now - req.submitted_at
                if waited < pol.hedge_ttft_frac * req.ttft_deadline_s:
                    continue
                target = self._route(req, now, exclude=rep.name)
                if target is None:
                    continue            # nowhere to hedge right now
                self._hedge_to(target, rep, req, now, waited)

    def _hedge_to(self, target: Replica, primary: Replica,
                  req: GatewayRequest, now: float, waited: float):
        ctx = req.trace.child() if req.trace is not None else None
        try:
            rid2 = target.engine.add_request(
                req.prompt,
                req.dispatch_max_new or req.max_new_tokens,
                on_token=self._make_on_token(target, req), trace_ctx=ctx,
                **req.sampling)
        except TransientDispatchError as e:
            # a failed hedge is best-effort: count it on the target's
            # breaker, burn no retry budget — the primary attempt is
            # still running
            self._breaker_failure(target.name, now, repr(e))
            return
        except (ValueError, TypeError, NotImplementedError) as e:
            self._log.debug("gateway: hedge dispatch to %s rejected: %r",
                            target.name, e)
            return
        self._breaker_note_dispatch(target.name, now, gid=req.gid)
        req.hedged = True
        req.hedge_replica = target.name
        req.hedge_rid = rid2
        target.inflight[rid2] = req
        self._hedges_live += 1
        self._rstats.add("hedges")
        self._remit("hedge", gid=req.gid, primary=primary.name,
                    hedge=target.name, waited_s=round(waited, 6),
                    ttft_deadline_s=req.ttft_deadline_s,
                    **self._trace_fields(req, ctx))

    def _resolve_hedge(self, req: GatewayRequest, winner_is_hedge: bool):
        """First token arrived while two attempts were racing: promote
        the winning attempt into the request's primary fields and cancel
        the loser (its terminal signal is identity-swallowed — no
        double delivery, no double finalize)."""
        if winner_is_hedge:
            loser_name, loser_rid = req.replica, req.engine_rid
            req.replica, req.engine_rid = req.hedge_replica, req.hedge_rid
            self._rstats.add("hedges_won")
            what = "hedge_won"
        else:
            loser_name, loser_rid = req.hedge_replica, req.hedge_rid
            self._rstats.add("hedges_lost")
            what = "hedge_lost"
        req.hedge_replica = req.hedge_rid = None
        self._hedges_live -= 1
        self._remit(what, gid=req.gid, winner=req.replica,
                    loser=loser_name)
        self._cancel_attempt(loser_name, loser_rid)

    def _abort_hedge(self, req: GatewayRequest):
        """Tear down a still-racing hedge attempt (terminal transition,
        quarantine of its replica): cancel and clear — no winner, no
        consumer signal (no tokens were streamed while racing)."""
        if req.hedge_rid is None:
            return
        loser_name, loser_rid = req.hedge_replica, req.hedge_rid
        req.hedge_replica = req.hedge_rid = None
        self._hedges_live -= 1
        self._rstats.add("hedges_aborted")
        self._cancel_attempt(loser_name, loser_rid)

    def _cancel_attempt(self, replica_name: Optional[str],
                        rid: Optional[int]):
        rep = (None if replica_name is None
               else self._replicas.get(replica_name))
        if rep is None or rid is None:
            return
        rep.inflight.pop(rid, None)
        try:
            rep.engine.cancel(rid)
        except Exception as e:  # noqa: BLE001 — a wedged loser replica
            # must not break the winner's stream; its state is
            # best-effort host bookkeeping
            self._log.debug("gateway: losing-attempt cancel on %s "
                            "failed: %r", replica_name, e)

    def _harvest(self):
        for rep in self._replicas.values():
            self._harvest_replica(rep)

    def _harvest_replica(self, rep: Replica):
        if not hasattr(rep.engine, "pop_finished"):
            return
        try:
            finished = rep.engine.pop_finished()
        except Exception as e:  # noqa: BLE001 — harvest re-enters the
            # engine (the quarantine path re-enters the very engine whose
            # step() just raised); a broken pop_finished must not escape
            # the isolation that routed us here
            self._log.warning("gateway: pop_finished on %s raised: %r — "
                              "skipping harvest this round", rep.name, e)
            return
        for rid, tokens in finished.items():
            req = rep.inflight.pop(rid, None)
            if req is None:
                continue            # not gateway-managed (direct client)
            if req.done or not (rep.name == req.replica
                                and rid == req.engine_rid):
                continue    # stale/losing attempt retired late: the
                #             winner owns the stream and the finalize
            req.tokens = list(tokens)       # engine list is authoritative
            self._breaker_success(rep.name)
            if req.first_token_at is not None:
                ttft = req.first_token_at - req.submitted_at
                self._stats.observe("ttft_seconds", ttft)
                if self._slo is not None:
                    self._slo.observe("ttft_s", ttft)
            self._finalize(req, "finished", self._clock(), signal=False)
            self._finished[req.gid] = req.tokens

    # ------------------------------- disaggregated prefill/decode -------
    # (docs/KV_TIERING.md: prompt prefills on a `prefill` replica, the
    # resulting KV pages migrate under a byte budget into a `decode`
    # replica's TieredKVStore, then the request dispatches there and
    # admission restores the pages device-side.  Every failure falls
    # back to plain recompute dispatch — slower, never wrong.)

    def _kvemit(self, what: str, **fields):
        """A ``kvstore`` tracer event (migration/fallback transitions —
        docs/OBSERVABILITY.md table)."""
        if self.tracer is None:
            return
        self.tracer.emit("kvstore", what=what, **fields)

    def _disagg_route(self, req: GatewayRequest, now: float
                      ) -> Optional[Replica]:
        """The pipeline's admission gate: an ACTIVE ``prefill`` replica
        with headroom, for a prompt wide enough to export (>= 2 full
        blocks — the last bucket block is always recomputed, so anything
        narrower migrates nothing), with at least one page-receiving
        destination alive.  None -> the normal (recompute) path."""
        if req.no_disagg or req.gid in self._disagg:
            return None
        preps = [rep for rep in self._replicas.values()
                 if rep.state == ACTIVE and rep.role == "prefill"
                 and rep.slots_available() > 0
                 and self._breaker_allows(rep.name, now)]
        if not preps:
            return None
        cands = [rep for rep in preps
                 if self._exportable(rep.engine, req.prompt)]
        if not cands:
            return None
        if not any(rep.state == ACTIVE and rep.role != "prefill"
                   and getattr(rep.engine, "kv_store", None) is not None
                   for rep in self._replicas.values()):
            return None
        # LAST (it is the only chain-digest walk here): a routable
        # replica that ALREADY covers the prompt (full depth in any
        # tier) makes the pipeline pure overhead — the tier-aware
        # router sends the request straight to the warm replica, and
        # _route's scoring walk right after is the one that actually
        # uses the warmth; re-prefilling and re-migrating resident
        # pages would only burn budget and a prefill turn
        for rep in self._replicas.values():
            if rep.state != ACTIVE or rep.role == "prefill":
                continue
            bs = getattr(rep.engine, "bs", None)
            if isinstance(bs, int) and bs >= 1:
                m = self._match_of(rep, req)
                if (m["total"] + 1) * bs >= len(req.prompt):
                    return None
        return min(cands, key=lambda rep: rep.outstanding_tokens())

    @staticmethod
    def _exportable(engine, prompt: List[int]) -> bool:
        """Cheap width gate: the prompt spans >= 2 of the engine's KV
        blocks, so at least one full block sits below the
        always-recomputed last one.  Engines without a block size
        (contiguous) never qualify."""
        bs = getattr(engine, "bs", None)
        if not isinstance(bs, int) or bs < 1:
            return False
        return len(prompt) >= 2 * bs

    def _begin_prefill(self, prep: Replica, req: GatewayRequest,
                       now: float) -> bool:
        """Dispatch the gateway-internal prefill attempt (max_new 1 —
        the admission prefill IS the work; the sampled token is
        discarded, the decode replica re-derives it from the migrated
        pages, so the consumer stream is single-sourced).  False on any
        dispatch failure — the caller serves the request normally."""
        job = _DisaggJob(req, prep.name, now)

        def cb(_rid, tok, done, _job=job):
            # gateway-internal consumer: only terminal transitions
            # matter; a preemption replay signal (None, False) just
            # means the prefill reruns
            if tok is None and done:
                _job.prefill_failed = True         # cancelled under us
            elif done:
                _job.prefill_done = True

        ctx = req.trace.child() if req.trace is not None else None
        try:
            rid = prep.engine.add_request(req.prompt, 1, on_token=cb,
                                          trace_ctx=ctx, **req.sampling)
        except Exception as e:  # noqa: BLE001 — ANY prefill admission
            # failure (transient or structural) degrades to the normal
            # recompute path; the request is never lost to the pipeline
            self._log.debug("gateway: disagg prefill dispatch on %s "
                            "rejected (%r) — recompute path",
                            prep.name, e)
            return False
        self._breaker_note_dispatch(prep.name, now, gid=req.gid)
        job.prefill_rid = rid
        req.status = "dispatched"        # in the pipeline, not a queue
        with self._disagg_lock:
            self._disagg[req.gid] = job
        self._kvstats.add("prefill_dispatches")
        self._kvemit("prefill_start", gid=req.gid, replica=prep.name,
                     prompt_len=len(req.prompt),
                     **self._trace_fields(req, ctx))
        return True

    def _drop_job(self, job: _DisaggJob):
        """Remove the job and cancel its prefill attempt if still live
        (best-effort — a wedged prefill replica's host state must not
        block the fallback)."""
        with self._disagg_lock:
            self._disagg.pop(job.req.gid, None)
        if job.prefill_rid is not None and not job.prefill_done:
            src = self._replicas.get(job.src)
            if src is not None:
                try:
                    src.engine.cancel(job.prefill_rid)
                except Exception as e:  # noqa: BLE001 — best-effort
                    self._log.debug("gateway: disagg prefill cancel on "
                                    "%s failed: %r", job.src, e)
        # the internal prefill attempt never reaches _finalize/_harvest,
        # so a HALF_OPEN probe it claimed must be released HERE or the
        # prefill replica stays probe-locked (and pipeline-excluded)
        # forever; completion resolves it via _breaker_success instead
        cb = self._breaker(job.src)
        if cb is not None and cb.state == CircuitBreaker.HALF_OPEN \
                and cb.probe_gid == job.req.gid:
            cb.release_probe()

    def _disagg_fallback(self, job: _DisaggJob, reason: str):
        """Degrade to plain recompute: the request rejoins the FRONT of
        its priority queue (it has waited longest) flagged
        ``no_disagg``, and the normal router serves it — slower, never
        wrong, zero drops."""
        req = job.req
        self._drop_job(job)
        self._kvstats.add("migration_fallbacks")
        self._kvemit("fallback", gid=req.gid, reason=reason,
                     phase=job.phase, **self._trace_fields(req))
        self._log.debug("gateway: disagg pipeline for %d fell back (%s, "
                        "phase %s)", req.gid, reason, job.phase)
        req.no_disagg = True
        req.status = "queued"
        self._queues[req.priority].appendleft(req)
        self._queued_tokens[req.priority] += req.est_tokens

    def _pick_dest(self, job: _DisaggJob, now: float) -> bool:
        """Choose the page-receiving destination: ACTIVE non-prefill
        replicas with a kv_store whose page meta matches the exported
        pages; ``decode`` role preferred over ``unified``, least
        outstanding tokens within a role.  False when none qualifies."""
        meta = job.pages[0].meta if job.pages else None
        best = None
        for rep in self._replicas.values():
            if rep.state != ACTIVE or rep.role == "prefill":
                continue
            if getattr(rep.engine, "kv_store", None) is None:
                continue
            if meta is not None:
                try:
                    emeta = rep.engine.kv_page_meta()
                except Exception as e:  # noqa: BLE001 — an engine that
                    # cannot state its page meta cannot receive pages
                    self._log.debug("gateway: kv_page_meta on %s failed: "
                                    "%r", rep.name, e)
                    continue
                from .kv_store import _freeze_meta
                if _freeze_meta(emeta) != meta:
                    continue
            key = (rep.role != "decode", rep.outstanding_tokens())
            if best is None or key < best[0]:
                best = (key, rep)
        if best is None:
            return False
        job.dest = best[1].name
        return True

    def _advance_disagg(self, now: float):
        """One tick of every disaggregated pipeline: deadlines/timeouts,
        prefill completion -> page export -> budgeted migration chunks ->
        handoff dispatch.  Runs after harvest so a prefill that finished
        THIS tick exports immediately."""
        with self._disagg_lock:
            jobs = list(self._disagg.items())
        for gid, job in jobs:
            req = job.req
            if req.done:                 # cancelled/finalized elsewhere
                self._drop_job(job)
                continue
            waited = now - req.submitted_at
            kind = None
            if req.deadline_s is not None and waited > req.deadline_s:
                kind = "total"
            elif (req.ttft_deadline_s is not None
                    and waited > req.ttft_deadline_s):
                kind = "ttft"
            if kind is not None:
                self._drop_job(job)
                req.error = DeadlineExceeded(
                    kind, req.deadline_s if kind == "total"
                    else req.ttft_deadline_s, waited, 0)
                self._stats.add(f"expired_{kind}")
                self._emit("expired", gid=gid, deadline=kind,
                           waited_s=waited, where="migration",
                           **self._trace_fields(req))
                self._finalize(req, "expired", now)
                continue
            if now - job.phase_at > self.stall_threshold_s:
                self._disagg_fallback(job, f"{job.phase} timed out")
                continue
            if job.phase == "prefill":
                src = self._replicas.get(job.src)
                if src is None or src.state not in (ACTIVE, DRAINING) \
                        or job.prefill_failed:
                    self._disagg_fallback(job, "prefill replica lost")
                    continue
                if not job.prefill_done:
                    continue
                # a delivered prefill is a delivered dispatch: resolve
                # the breaker (closing a HALF_OPEN probe this attempt
                # claimed — harvest never sees the internal rid)
                self._breaker_success(job.src)
                try:
                    pages = src.engine.export_prefix_pages(req.prompt)
                except Exception as e:  # noqa: BLE001 — export is
                    # best-effort: recompute is always available
                    self._log.debug("gateway: page export on %s failed: "
                                    "%r", job.src, e)
                    pages = []
                if not pages:
                    self._disagg_fallback(job, "no exportable pages")
                    continue
                from .kv_store import PageMigration
                job.pages = pages
                job.migration = PageMigration(
                    pages, self.migration_bytes_per_tick)
                if not self._pick_dest(job, now):
                    self._disagg_fallback(
                        job, "no page-receiving decode replica")
                    continue
                job.phase = "migrate"
                job.phase_at = now
                self._kvstats.add("migrations_started")
                self._kvemit("migrate_start", gid=gid, src=job.src,
                             dest=job.dest, pages=len(pages),
                             bytes=job.migration.total_bytes,
                             **self._trace_fields(req))
                # fall through: the first chunk moves this very tick
            if job.phase == "migrate":
                dest = self._replicas.get(job.dest)
                if dest is None or dest.state != ACTIVE \
                        or getattr(dest.engine, "kv_store", None) is None:
                    # destination lost mid-transfer: RESUME into another
                    # one (pages live host-side in the plan), or degrade
                    old = job.dest
                    if not self._pick_dest(job, now):
                        self._disagg_fallback(job, "destination lost")
                        continue
                    job.migration.restart()
                    self._kvemit("migrate_resume", gid=gid,
                                 from_dest=old, dest=job.dest,
                                 **self._trace_fields(req))
                    dest = self._replicas[job.dest]
                moved0 = job.migration.transferred_bytes
                delivered = job.migration.advance()
                if job.migration.transferred_bytes > moved0:
                    # BYTE progress is liveness (a page wider than the
                    # budget spans many ticks with nothing delivered):
                    # the stall timeout bounds no-progress time, never
                    # total transfer time
                    job.phase_at = now
                ok = True
                for page in delivered:
                    try:
                        dest.engine.kv_store.put(page)
                    except Exception as e:  # noqa: BLE001 — a broken
                        # store degrades to recompute, never corrupts
                        self._log.debug("gateway: page delivery to %s "
                                        "failed: %r", job.dest, e)
                        ok = False
                        break
                if not ok:
                    self._disagg_fallback(job, "page delivery failed")
                    continue
                if delivered:
                    self._kvstats.add("migrated_pages", len(delivered))
                    self._kvstats.add("migrated_bytes",
                                      sum(p.nbytes for p in delivered))
                if not job.migration.done:
                    continue
                job.phase = "handoff"
                job.phase_at = now
                self._kvstats.add("migrations_completed")
                self._kvemit("migrate_done", gid=gid, dest=job.dest,
                             bytes=job.migration.total_bytes,
                             ticks=job.migration.ticks,
                             **self._trace_fields(req))
            if job.phase == "handoff":
                dest = self._replicas.get(job.dest)
                if dest is None or dest.state != ACTIVE:
                    self._disagg_fallback(job,
                                          "destination lost at handoff")
                    continue
                if req.not_before is not None and now < req.not_before:
                    continue             # retry backoff (resilience)
                if dest.slots_available() <= 0 \
                        or not self._breaker_allows(dest.name, now):
                    continue             # wait for headroom
                held = self._dispatch_to(dest, req, now)
                if held is None:
                    # dispatched (admission will restore the migrated
                    # pages), or terminally failed inside _dispatch_to —
                    # either way the pipeline is done with it
                    with self._disagg_lock:
                        self._disagg.pop(gid, None)

    def decode_pool_pressure(self) -> float:
        """Occupancy of the DECODE pool: (in-flight + queued + migrating)
        over ACTIVE non-prefill slots — the autoscaler's
        disaggregation-aware scale-up signal (prefill replicas can sit
        idle while the decode pool drowns; fleet-wide occupancy would
        average that away)."""
        reps = [r for r in self._replicas.values()
                if r.state == ACTIVE and r.role != "prefill"]
        slots = sum(_engine_slots(r.engine) for r in reps)
        busy = sum(len(r.inflight) for r in reps)
        with self._disagg_lock:
            migrating = len(self._disagg)
        queued = sum(len(q) for q in self._queues) + migrating
        return (busy + queued) / max(slots, 1)

    def prefix_index(self, prompt=None) -> Dict[str, Dict[str, Any]]:
        """The FLEET-WIDE prefix index (ROADMAP item 1): per-replica
        tier-aware views through the engines' PUBLIC prefix API.
        Without a prompt: each live replica's resident-page census
        (``{"pages": {tier: count}}``).  With one: each replica's
        tier-aware depth map for THAT prompt — exactly what the router
        scores, exposed for operators, the ops ``/kvstore`` view and
        tests."""
        out: Dict[str, Dict[str, Any]] = {}
        for name, rep in self._replicas.items():
            if rep.state not in (ACTIVE, DRAINING):
                continue
            entry: Dict[str, Any] = {"role": rep.role,
                                     "state": rep.state}
            if prompt is not None:
                entry.update(self._prefix_match(rep.engine,
                                                [int(t) for t in prompt]))
            else:
                tiers: Dict[str, int] = {}
                idx_fn = getattr(rep.engine, "prefix_index", None)
                if idx_fn is not None:
                    try:
                        for tier in idx_fn().values():
                            tiers[tier] = tiers.get(tier, 0) + 1
                    except Exception as e:  # noqa: BLE001 — census is
                        # advisory, a broken engine view reads as empty
                        self._log.debug("gateway: prefix_index on %s "
                                        "failed: %r", name, e)
                entry["pages"] = tiers
            out[name] = entry
        return out

    def _kv_stores(self):
        """Distinct attached stores (decode replicas may share one)."""
        stores, seen = [], set()
        for rep in self._replicas.values():
            st = getattr(rep.engine, "kv_store", None)
            if st is not None and id(st) not in seen:
                seen.add(id(st))
                stores.append(st)
        return stores

    def has_kv_surface(self) -> bool:
        with self._disagg_lock:
            migrating = bool(self._disagg)
        return (migrating or bool(self._kvstats.snapshot())
                or bool(self._kv_stores())
                or any(rep.role != "unified"
                       for rep in self._replicas.values()))

    def kvstore_snapshot(self) -> Dict[str, Any]:
        """JSON-able live KV-tiering view — what ``GET /kvstore``
        serves: migration counters + in-flight pipelines, per-replica
        role/store state, the fleet prefix index."""
        replicas = {}
        for name, rep in self._replicas.items():
            store = getattr(rep.engine, "kv_store", None)
            replicas[name] = {
                "role": rep.role, "state": rep.state,
                "store": None if store is None else store.snapshot()}
        with self._disagg_lock:
            jobs = list(self._disagg.values())
        return {
            "migration_bytes_per_tick": self.migration_bytes_per_tick,
            "migrations_inflight": [job.to_dict() for job in jobs],
            "counters": dict(self._kvstats.snapshot()),
            "decode_pool_pressure": round(self.decode_pool_pressure(), 4),
            "replicas": replicas,
            "prefix_index": self.prefix_index(),
        }

    def _reroute_inflight(self, rep: Replica):
        """Quarantine re-admission: completed work is harvested (never
        replayed), everything else is cancelled on the replica and
        re-queued at the FRONT of its priority queue, oldest first, after
        the documented replay signal."""
        self._harvest_replica(rep)
        moved = sorted(rep.inflight.items(),
                       key=lambda kv: kv[1].submitted_at, reverse=True)
        for rid, req in moved:
            if req.done:
                rep.inflight.pop(rid, None)
                continue
            if self._drop_hedge_twin(rep, rid, req):
                continue        # the other racing attempt carries on
            req._rerouting = True
            try:
                rep.engine.cancel(rid)
            except Exception as e:  # noqa: BLE001 — a wedged replica's
                # host state is best-effort; the request reroutes anyway
                self._log.debug("gateway: cancel on quarantined %s "
                                "failed: %r", rep.name, e)
            finally:
                req._rerouting = False
            rep.inflight.pop(rid, None)
            req.engine_rid = None
            req.replica = None
            req.tokens = []
            req.first_token_at = None
            req.replays += 1
            req.status = "queued"
            if req.on_token is not None:
                try:
                    req.on_token(req.gid, None, False)     # replay signal
                except Exception:  # noqa: BLE001 — a raising consumer must
                    # not strand the replica's remaining in-flight requests
                    self._log.exception(
                        "gateway on_token replay signal failed for %d",
                        req.gid)
            self._queues[req.priority].appendleft(req)
            self._queued_tokens[req.priority] += req.est_tokens
            self._stats.add("rerouted")
            self._emit("reroute", gid=req.gid, from_replica=rep.name,
                       **self._trace_fields(req))

    def _drop_hedge_twin(self, rep: Replica, rid: int,
                         req: GatewayRequest) -> bool:
        """Quarantine hit ONE attempt of a still-racing hedged request:
        drop just that attempt and let the twin on the healthy replica
        carry the request — no re-queue, no replay signal (no tokens
        were streamed while racing).  False when the request is not a
        racing hedge on this replica (the normal reroute applies)."""
        if req.hedge_rid is None:
            return False
        if rep.name == req.replica and rid == req.engine_rid:
            # the primary died: promote the hedge attempt
            req.replica, req.engine_rid = req.hedge_replica, req.hedge_rid
        elif not (rep.name == req.hedge_replica
                  and rid == req.hedge_rid):
            return False
        req.hedge_replica = req.hedge_rid = None
        self._hedges_live -= 1
        self._rstats.add("hedges_aborted")
        req._rerouting = True
        try:
            rep.engine.cancel(rid)
        except Exception as e:  # noqa: BLE001 — the quarantined host
            # state is best-effort; the surviving attempt carries on
            self._log.debug("gateway: hedge-twin cancel on %s failed: %r",
                            rep.name, e)
        finally:
            req._rerouting = False
        rep.inflight.pop(rid, None)
        self._remit("hedge_twin_dropped", gid=req.gid,
                    quarantined=rep.name, survivor=req.replica)
        return True

    def _unqueue(self, req: GatewayRequest):
        q = self._queues[req.priority]
        try:
            q.remove(req)
        except ValueError:
            return
        self._queued_tokens[req.priority] -= req.est_tokens

    def _finalize(self, req: GatewayRequest, status: str, now: float,
                  signal: bool = True):
        """Terminal transition.  ``signal=True`` delivers the clean
        end-of-stream ``on_token(gid, None, True)`` to the consumer —
        every early termination (shed/expired/cancelled/failed) signals;
        natural completion does not (the engine already delivered the
        last token with ``done=True``)."""
        self._abort_hedge(req)      # a racing twin never outlives its
        req.status = status         # request (no-op when not hedging)
        if status != "finished" and req.first_token_at is None \
                and req.replica is not None:
            # the attempt ended without ever delivering: a HALF_OPEN
            # probe must not stay claimed forever (the replica would be
            # silently lost from routing).  Keyed to the probe REQUEST's
            # identity — an unrelated pre-open in-flight request
            # terminating token-less must neither free nor fail a probe
            # it never held.  A deadline expiry IS the probe's verdict
            # (the replica failed to deliver in time); a client cancel
            # is nobody's fault — just free the claim.
            cb = self._breaker(req.replica)
            if cb is not None and cb.state == CircuitBreaker.HALF_OPEN \
                    and cb.probe_gid == req.gid:
                if status == "expired":
                    self._breaker_failure(req.replica, now,
                                          "half-open probe expired")
                else:
                    cb.release_probe()
        req.finished_at = now
        self._stats.add(status)
        if self._slo is not None:
            self._slo.count(status)
        if status == "finished":
            # the trace's explicit terminal marker (shed/expired/cancel/
            # failed already emit their own) — the stitched root span
            # ends here
            self._emit("finish", gid=req.gid, tokens=len(req.tokens),
                       replica=req.replica, replays=req.replays,
                       **self._trace_fields(req))
        self._terminal_order.append(req.gid)
        while len(self._terminal_order) > self.request_history:
            old = self._terminal_order.popleft()
            stale = self._requests.get(old)
            if stale is not None and stale.done:
                del self._requests[old]
        if signal and req.on_token is not None:
            try:
                req.on_token(req.gid, None, True)
            except Exception:  # noqa: BLE001 — consumer bugs must not
                # break the dispatch loop
                self._log.exception(
                    "gateway on_token terminal signal failed for %d",
                    req.gid)

    def _emit(self, what: str, **fields):
        if self.tracer is None:
            return
        self.tracer.emit("gateway", what=what, **fields)

    def _remit(self, what: str, **fields):
        """A ``resilience`` tracer event (breaker/retry/hedge/brownout
        transitions — docs/OBSERVABILITY.md table)."""
        if self.tracer is None:
            return
        self.tracer.emit("resilience", what=what, **fields)

    # -------------------------------------------------- circuit breakers --

    def _breaker(self, name: str) -> Optional[CircuitBreaker]:
        return self._breakers.get(name) if self._breakers else None

    def _breaker_allows(self, name: str, now: float) -> bool:
        cb = self._breaker(name)
        if cb is None:
            return True
        prev = cb.state
        ok = cb.allow(now)
        if prev == CircuitBreaker.OPEN and cb.state == CircuitBreaker.HALF_OPEN:
            self._rstats.add("breaker_probes")
            self._remit("breaker_half_open", replica=name)
        return ok

    def _breaker_note_dispatch(self, name: str, now: float,
                               gid: Optional[int] = None):
        cb = self._breaker(name)
        if cb is not None:
            cb.note_dispatch(now, gid=gid)

    def _breaker_failure(self, name: str, now: float, reason: str):
        cb = self._breaker(name)
        if cb is None:
            return
        if cb.record_failure(now):
            self._rstats.add("breaker_opens")
            self._remit("breaker_open", replica=name, reason=reason,
                        consecutive_failures=cb.consecutive_failures)
            self._log.warning("gateway: circuit breaker OPEN on %s (%s)",
                              name, reason)

    def _breaker_success(self, name: str):
        cb = self._breaker(name)
        if cb is None:
            return
        if cb.record_success():
            self._rstats.add("breaker_closes")
            self._remit("breaker_close", replica=name)

    def breakers_open(self) -> List[str]:
        """Names of ACTIVE replicas whose circuit breaker is OPEN and
        still inside its window right now — the autoscaler consumes this
        as a scale-up signal alongside firing SLOs (a broken replica is
        missing capacity even before the SLO math notices).  An OPEN
        breaker past its window is one routing inquiry from HALF_OPEN,
        so it stops counting — with no traffic, nothing ever routes, and
        a stale signal would otherwise pin an idle fleet at max size
        forever.  Only ACTIVE replicas count: a
        quarantined/stopped replica's breaker can never half-open (the
        routing probe is the only OPEN→HALF_OPEN path), and its missing
        capacity is already the quarantine-reap/min-bound machinery's
        problem — counting it here would turn one quarantine into a
        PERMANENT scale-up signal.  Empty without a resilience policy."""
        now = self._clock()
        return sorted(
            name for name, cb in self._breakers.items()
            if cb.effectively_open(now)
            and (rep := self._replicas.get(name)) is not None
            and rep.state == ACTIVE)

    # ----------------------------------------------------------- brownout --

    def _occupancy(self) -> float:
        """Fleet pressure: (in-flight + queued) requests over total
        ACTIVE engine slots — the same occupancy the autoscaler's
        scale-down signal reads."""
        return self._occupancy_terms()["value"]

    def _occupancy_terms(self) -> Dict[str, Any]:
        """Occupancy with its raw terms (busy/slots/queued) — the
        ``occupancy`` block of ``gateway_snapshot()``."""
        active = [rep for rep in self._replicas.values()
                  if rep.state == ACTIVE]
        slots = sum(_engine_slots(rep.engine) for rep in active)
        busy = sum(len(rep.inflight) for rep in active)
        queued = sum(len(q) for q in self._queues)
        return {"value": round((busy + queued) / max(slots, 1), 4),
                "busy_slots": busy, "total_slots": slots,
                "queued": queued}

    def _evaluate_brownout(self, now: float):
        pressure = self._occupancy()
        slo_firing = False
        if self.resilience.brownout_use_slo and self._slo is not None:
            try:
                slo_firing = any(
                    state == "firing"
                    for state in self._slo.alert_states().values())
            except Exception as e:  # noqa: BLE001 — a broken monitor
                # must not stall the admission plane
                self._log.debug("gateway: slo poll failed: %r", e)
        delta = self._brownout.evaluate(now, pressure, slo_firing)
        if delta == 0:
            return
        lvl = self._brownout.level
        self._rstats.add("brownout_ups" if delta > 0 else "brownout_downs")
        self._remit("brownout_up" if delta > 0 else "brownout_down",
                    level=lvl, label=BROWNOUT_LEVELS[lvl],
                    pressure=round(pressure, 4), slo_firing=slo_firing)
        self._log.warning("gateway: brownout %s to level %d (%s), "
                          "pressure=%.2f", "UP" if delta > 0 else "down",
                          lvl, BROWNOUT_LEVELS[lvl], pressure)

    @property
    def brownout_level(self) -> int:
        """Current brownout rung (0 = normal; index into
        :data:`BROWNOUT_LEVELS`)."""
        return 0 if self._brownout is None else self._brownout.level

    def resilience_snapshot(self) -> Optional[Dict[str, Any]]:
        """JSON-able live resilience view — what ``ops_server``'s
        ``/resilience`` route serves and the FlightRecorder dumps:
        policy knobs, per-replica breaker states, the brownout rung,
        live hedges, and every resilience counter.  None when no
        resilience policy is attached."""
        if self.resilience is None:
            return None
        return {
            "policy": self.resilience.to_dict(),
            "breakers": {name: cb.to_dict()
                         for name, cb in sorted(self._breakers.items())},
            "breakers_open": self.breakers_open(),
            "brownout": (None if self._brownout is None
                         else self._brownout.to_dict()),
            "hedges_inflight": self._hedges_live,
            "occupancy": round(self._occupancy(), 4),
            "counters": dict(self._rstats.snapshot()),
        }

    # --------------------------------------------------------- telemetry --

    def queue_depths(self) -> Dict[int, Dict[str, int]]:
        return {pri: {"depth": len(q),
                      "queued_tokens": self._queued_tokens[pri]}
                for pri, q in enumerate(self._queues)}

    def gateway_snapshot(self) -> Dict[str, Any]:
        """JSON-able live view — what ``ops_server``'s ``/gateway`` route
        serves: replica states, queue depths, counters, latency
        percentiles."""
        h_q = self._stats.histogram("queue_seconds")
        h_t = self._stats.histogram("ttft_seconds")
        counters = {k: v for k, v in self._stats.snapshot().items()}
        out = {
            "replicas": [rep.to_dict() for rep in self._replicas.values()],
            "queues": self.queue_depths(),
            "counters": counters,
            # bucket-resolution estimates (utils.stats.Histogram); exact
            # sample percentiles ride the tracer / request handles
            "queue_s": {"p50": h_q.percentile(0.50),
                        "p99": h_q.percentile(0.99)},
            "ttft_s": {"p50": h_t.percentile(0.50),
                       "p99": h_t.percentile(0.99)},
            # fleet pressure with its raw terms — what a FleetCollector
            # reads per target (resilience carries the same scalar, but
            # only when a resilience policy is configured)
            "occupancy": self._occupancy_terms(),
        }
        if self.resilience is not None:
            # breaker/brownout state rides every snapshot consumer —
            # /gateway, and the FlightRecorder's crash dumps
            out["resilience"] = self.resilience_snapshot()
        if self.has_kv_surface():
            with self._disagg_lock:
                migrating = len(self._disagg)
            # the light view; GET /kvstore serves the full one
            out["kvstore"] = {
                "counters": dict(self._kvstats.snapshot()),
                "migrations_inflight": migrating,
                "decode_pool_pressure": round(
                    self.decode_pool_pressure(), 4)}
        return out

    summary = gateway_snapshot

    def metrics(self) -> Dict[str, float]:
        out = dict(self._stats.snapshot())
        out["queued"] = float(sum(len(q) for q in self._queues))
        out["inflight"] = float(sum(len(rep.inflight)
                                    for rep in self._replicas.values()))
        return out

    def prometheus_text(self, namespace: str = "paddle_tpu_gateway") -> str:
        text = _prometheus_text(
            self._stats, namespace=namespace,
            extra_gauges={
                "queued": sum(len(q) for q in self._queues),
                "inflight": sum(len(rep.inflight)
                                for rep in self._replicas.values()),
                "replicas_active": sum(
                    1 for rep in self._replicas.values()
                    if rep.state == ACTIVE)})
        if self.resilience is not None:
            breakers = list(self._breakers.values())
            text += _prometheus_text(
                self._rstats, namespace="paddle_tpu_resilience",
                extra_gauges={
                    "brownout_level": self.brownout_level,
                    "breakers_open": sum(
                        1 for cb in breakers
                        if cb.state == CircuitBreaker.OPEN),
                    "breakers_half_open": sum(
                        1 for cb in breakers
                        if cb.state == CircuitBreaker.HALF_OPEN),
                    "hedges_inflight": self._hedges_live})
        if self.has_kv_surface():
            # fleet-aggregated tier gauges (stores deduped — decode
            # replicas may share one) under the kvstore namespace
            tier = {"dram_pages": 0.0, "dram_bytes": 0.0,
                    "disk_pages": 0.0, "disk_bytes": 0.0}
            for st in self._kv_stores():
                m = st.metrics()
                for k in tier:
                    tier[k] += float(m.get(k, 0.0))
            with self._disagg_lock:
                migrating = len(self._disagg)
            text += _prometheus_text(
                self._kvstats, namespace="paddle_tpu_kvstore",
                extra_gauges={
                    "migrations_inflight": migrating,
                    "decode_pool_pressure": self.decode_pool_pressure(),
                    **tier})
        return text
