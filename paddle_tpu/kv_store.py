"""Tiered KV page store + migration planner for the serving fleet.

The paged engines' prefix cache (serving_paged.py) keeps shared-prompt
k/v blocks in HBM and *drops* them on eviction — a long system prompt
that falls out of one replica's pool is recomputed from scratch, per
replica, forever.  This module is the missing storage hierarchy and the
transport between replicas:

- :class:`KVPage` — ONE block's k/v for every layer, addressed by its
  prefix-cache **chain digest** (serving_paged's rolling blake2b over
  (pad, tokens)), carrying the metadata that makes it portable: layer
  count, block size, per-leaf dtype + shape (int8 pools ship their fp32
  scale planes as just another leaf).  A page is addressed by *content*,
  not by the request or replica that produced it — the Ragged Paged
  Attention block-table layout (PAPERS.md) makes pages portable by
  construction, and this class is that portability made explicit.
- :class:`TieredKVStore` — host DRAM (LRU `OrderedDict`, byte-capped)
  over disk (one file per page, byte-capped): ``put`` lands in DRAM and
  demotes the DRAM LRU tail to disk when over budget (or drops it when
  no disk tier is configured); ``lookup`` promotes a disk hit back into
  DRAM; a corrupt or metadata-mismatched page is a MISS, never a wrong
  page — the consumer falls back to recompute, which is always correct.
  ``tier_of``/``index`` are pure reads (no LRU touch) for the routing
  plane (the gateway's tier-aware prefix index).
- :class:`PageMigration` — the prefill→decode transfer schedule: pages
  move in chain order under a **byte budget per tick** (the
  array-redistribution discipline of "Memory-efficient array
  redistribution through portable collective communication", PAPERS.md:
  an explicit, budgeted schedule, not an ad-hoc copy), resumable —
  ``restart()`` replays the whole page list into a new destination when
  the first one is quarantined mid-transfer.

Everything here is numpy + stdlib — importing it never touches JAX, so
the fake-clock simulation tests (tests/test_kv_store.py) and the
gateway's migration driver stay millisecond-cheap.  The device-side
gather/scatter that turns a pool block into a page (and back) lives
with the engines in serving_paged.py.

No reference counterpart: the reference snapshot serves static batches
with no cache hierarchy at all (SURVEY §2.3); this is the
millions-of-users warm-prompt architecture (ROADMAP item 1).
"""

from __future__ import annotations

import collections
import hashlib
import io
import json
import logging
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from .telemetry_memory import current_memory_ledger
from .utils.stats import StatRegistry, prometheus_text as _prometheus_text

__all__ = ["KVPage", "TieredKVStore", "PageMigration", "chain_hex"]

#: tier labels, warmest first — the routing plane's vocabulary
TIERS = ("hbm", "dram", "disk")


def chain_hex(chain) -> str:
    """JSON-able form of a chain key: digest chains render as hex, the
    sim engines' string chains as-is — ONE spelling for every
    index/snapshot consumer."""
    if isinstance(chain, (bytes, bytearray)):
        return bytes(chain).hex()
    return str(chain)


class KVPage:
    """One portable KV block: ``chain`` (the prefix-cache chain digest),
    ``payload`` (a tuple of numpy arrays — one per cache-pool leaf, so
    int8 value planes and their fp32 scale planes ride together — or
    raw ``bytes`` for host-only simulation pages), and ``meta`` (the
    JSON-able signature the producing engine emits from
    ``kv_page_meta()``: block size plus per-leaf dtype/shape).  Pages
    with mismatched meta never restore — a store shared across engine
    configs serves only compatible pages."""

    __slots__ = ("chain", "payload", "meta")

    def __init__(self, chain, payload, meta):
        if not isinstance(chain, (bytes, str)):
            # chains must survive the disk tier's serialization losslessly
            # (the integrity check compares them); digests are bytes, the
            # sim engines use strings
            raise TypeError(f"chain must be bytes or str, got "
                            f"{type(chain).__name__}")
        if isinstance(payload, (bytes, bytearray)):
            payload = bytes(payload)
        else:
            payload = tuple(np.asarray(a) for a in payload)
        self.chain = chain
        self.payload = payload
        self.meta = _freeze_meta(meta)

    @property
    def nbytes(self) -> int:
        if isinstance(self.payload, bytes):
            return len(self.payload)
        return int(sum(a.nbytes for a in self.payload))

    # --------------------------------------------------- serialization --
    # raw bytes + an explicit per-array (dtype name, shape) header, NOT
    # np.savez: savez round-trips ml_dtypes extension dtypes (bfloat16,
    # fp8) as raw void '|V2' arrays, which the meta check cannot catch
    # (it compares dtype STRINGS, which survive) — the broken payload
    # would then crash the engine mid-restore instead of missing.

    def to_bytes(self) -> bytes:
        """Self-describing page bytes: the chain, meta and payload
        round-trip bit-exactly for EVERY dtype (extension dtypes
        included) — the disk tier's on-disk format."""
        head = {"chain": chain_hex(self.chain),
                "chain_is_digest": isinstance(self.chain, bytes),
                "meta": self.meta}
        chunks: List[bytes] = []
        if isinstance(self.payload, bytes):
            head["kind"] = "bytes"
            chunks.append(self.payload)
            head["arrays"] = [len(self.payload)]
        else:
            head["kind"] = "arrays"
            specs = []
            for a in self.payload:
                raw = np.ascontiguousarray(a).tobytes()
                specs.append([str(a.dtype), list(a.shape), len(raw)])
                chunks.append(raw)
            head["arrays"] = specs
        hbytes = json.dumps(head).encode("utf-8")
        buf = io.BytesIO()
        buf.write(b"KVPG1")
        buf.write(len(hbytes).to_bytes(8, "little"))
        buf.write(hbytes)
        for raw in chunks:
            buf.write(raw)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "KVPage":
        if data[:5] != b"KVPG1":
            raise ValueError("not a KVPage container")
        hlen = int.from_bytes(data[5:13], "little")
        head = json.loads(data[13:13 + hlen].decode("utf-8"))
        chain = (bytes.fromhex(head["chain"])
                 if head["chain_is_digest"] else head["chain"])
        off = 13 + hlen
        if head["kind"] == "bytes":
            (n,) = head["arrays"]
            payload: Any = data[off:off + n]
            if len(payload) != n:
                raise ValueError("truncated KVPage payload")
        else:
            arrays = []
            for dtype_name, shape, n in head["arrays"]:
                raw = data[off:off + n]
                if len(raw) != n:
                    raise ValueError("truncated KVPage payload")
                arrays.append(np.frombuffer(
                    raw, dtype=_resolve_dtype(dtype_name))
                    .reshape(shape))
                off += n
            payload = tuple(arrays)
        return cls(chain, payload, head["meta"])

    def __repr__(self):
        return (f"KVPage(chain={chain_hex(self.chain)[:12]}…, "
                f"nbytes={self.nbytes})")


def _resolve_dtype(name: str) -> np.dtype:
    """Dtype from its string name, extension dtypes included: plain
    numpy rejects "bfloat16"/"float8_*" unless ml_dtypes is consulted —
    exactly the dtypes the TPU pools serialize."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _freeze_meta(meta):
    """Meta comparison must survive a JSON round trip (the disk tier):
    normalize tuples/lists to the JSON list form once, at construction,
    so ``page.meta == engine.kv_page_meta()`` after ``_freeze_meta`` on
    both sides is tier-independent."""
    return json.loads(json.dumps(meta))


class TieredKVStore:
    """Host-DRAM-over-disk page store (module docstring).

    ``dram_capacity_bytes`` bounds the DRAM tier; inserting past it
    demotes LRU pages to disk (``disk_dir``) or drops them when no disk
    tier is configured.  ``disk_capacity_bytes`` (optional) bounds the
    disk tier by evicting its oldest pages.  ``tracer``: optional
    :class:`~paddle_tpu.telemetry.Tracer` — demote/promote/evict emit
    structured ``kvstore`` events.  All methods are thread-safe (the
    gateway's dispatch thread and ops-server scrape threads share one
    store)."""

    def __init__(self, *, dram_capacity_bytes: int = 256 << 20,
                 disk_dir: Optional[str] = None,
                 disk_capacity_bytes: Optional[int] = None,
                 tracer=None, logger: Optional[logging.Logger] = None):
        if int(dram_capacity_bytes) < 1:
            raise ValueError("dram_capacity_bytes must be >= 1")
        if disk_capacity_bytes is not None and int(disk_capacity_bytes) < 1:
            raise ValueError("disk_capacity_bytes must be >= 1")
        self.dram_capacity_bytes = int(dram_capacity_bytes)
        self.disk_dir = None if disk_dir is None else str(disk_dir)
        self.disk_capacity_bytes = (None if disk_capacity_bytes is None
                                    else int(disk_capacity_bytes))
        self.tracer = tracer
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._lock = threading.Lock()
        self._dram: "collections.OrderedDict[Any, KVPage]" = \
            collections.OrderedDict()
        self._dram_bytes = 0
        # chain -> (path, nbytes); insertion order is the disk LRU
        self._disk: "collections.OrderedDict[Any, Tuple[str, int]]" = \
            collections.OrderedDict()
        self._disk_bytes = 0
        self._stats = StatRegistry()
        if self.disk_dir is not None:
            os.makedirs(self.disk_dir, exist_ok=True)

    # ------------------------------------------------------------ write --

    def put(self, page: KVPage) -> str:
        """Insert (or refresh) one page into the DRAM tier, demoting the
        DRAM LRU tail past capacity; returns the tier the page landed
        in (``"dram"`` — a page larger than the whole DRAM budget goes
        straight to disk, or is dropped without one)."""
        if not isinstance(page, KVPage):
            raise TypeError(f"put() wants a KVPage, got "
                            f"{type(page).__name__}")
        with self._lock:
            try:
                self._stats.add("puts")
                if page.nbytes > self.dram_capacity_bytes:
                    # same same-chain cleanup as the normal path: a stale
                    # DRAM copy left behind would SHADOW the fresh disk
                    # page on every later lookup
                    old = self._dram.pop(page.chain, None)
                    if old is not None:
                        self._dram_bytes -= old.nbytes
                    if self._spill_to_disk(page):
                        return "disk"
                    self._stats.add("evictions_dram")
                    return "dropped"
                old = self._dram.pop(page.chain, None)
                if old is not None:
                    self._dram_bytes -= old.nbytes
                self._drop_disk(page.chain)   # DRAM copy supersedes disk
                self._dram[page.chain] = page
                self._dram_bytes += page.nbytes
                self._enforce_dram()
                return "dram"
            finally:
                self._sync_memory()

    def _enforce_dram(self):
        while self._dram_bytes > self.dram_capacity_bytes and self._dram:
            chain, page = self._dram.popitem(last=False)      # LRU first
            self._dram_bytes -= page.nbytes
            if self._spill_to_disk(page):
                self._stats.add("demotions_disk")
                self._emit("demote", chain=chain_hex(chain),
                           bytes=page.nbytes, to="disk")
            else:
                self._stats.add("evictions_dram")
                self._emit("evict", chain=chain_hex(chain),
                           bytes=page.nbytes, tier="dram")

    def _spill_to_disk(self, page: KVPage) -> bool:
        if self.disk_dir is None:
            return False
        # file name = fixed-length digest of the chain, never a
        # truncation: long string chains (the sim engines') share
        # leading text, and truncated names would collide — the later
        # page overwriting the earlier and the integrity check then
        # deleting BOTH as corrupt
        fname = hashlib.blake2b(chain_hex(page.chain).encode("utf-8"),
                                digest_size=24).hexdigest()
        path = os.path.join(self.disk_dir, fname + ".kvpage")
        try:
            data = page.to_bytes()
            with open(path, "w+b") as f:
                f.write(data)
        except OSError as e:
            self._log.warning("kv_store: disk demotion failed (%r) — "
                              "page dropped", e)
            return False
        old = self._disk.pop(page.chain, None)
        if old is not None:
            self._disk_bytes -= old[1]
        self._disk[page.chain] = (path, len(data))
        self._disk_bytes += len(data)
        while (self.disk_capacity_bytes is not None
               and self._disk_bytes > self.disk_capacity_bytes
               and self._disk):
            victim, (vpath, vbytes) = self._disk.popitem(last=False)
            self._disk_bytes -= vbytes
            self._remove_file(vpath)
            self._stats.add("evictions_disk")
            self._emit("evict", chain=chain_hex(victim), bytes=vbytes,
                       tier="disk")
        return True

    def _drop_disk(self, chain):
        entry = self._disk.pop(chain, None)
        if entry is not None:
            self._disk_bytes -= entry[1]
            self._remove_file(entry[0])

    def _remove_file(self, path: str):
        try:
            os.remove(path)
        except OSError as e:
            self._log.debug("kv_store: stale page file %s not removed: %r",
                            path, e)

    # ------------------------------------------------------------- read --

    def lookup(self, chain, meta=None) -> Optional[KVPage]:
        """Fetch one page: a DRAM hit touches the LRU; a disk hit loads,
        verifies, and PROMOTES the page back into DRAM.  ``meta``
        (optional): the consumer's ``kv_page_meta()`` — a mismatch is a
        counted miss, never a wrong-shaped restore.  A corrupt disk page
        is dropped and counted; the caller recomputes."""
        frozen = None if meta is None else _freeze_meta(meta)
        with self._lock:
            page = self._dram.get(chain)
            if page is not None:
                if frozen is not None and page.meta != frozen:
                    self._stats.add("meta_mismatches")
                    return None
                self._dram.move_to_end(chain)
                self._stats.add("hits_dram")
                return page
            entry = self._disk.get(chain)
            if entry is None:
                self._stats.add("misses")
                return None
            path, nbytes = entry
            try:
                with open(path, "rb") as f:
                    page = KVPage.from_bytes(f.read())
                if page.chain != chain:
                    raise ValueError("chain mismatch in page file")
            except Exception as e:  # noqa: BLE001 — a corrupt page must
                # degrade to a MISS (recompute is always correct), never
                # to a wrong-page restore
                self._log.warning("kv_store: corrupt page %s dropped: %r",
                                  chain_hex(chain)[:16], e)
                self._disk.pop(chain, None)
                self._disk_bytes -= nbytes
                self._remove_file(path)
                self._stats.add("corrupt_pages")
                self._stats.add("misses")
                self._sync_memory()
                return None
            if frozen is not None and page.meta != frozen:
                self._stats.add("meta_mismatches")
                return None
            if page.nbytes > self.dram_capacity_bytes:
                # an oversized page stays disk-resident (put() sent it
                # straight there for the same reason): promoting it
                # would flush the ENTIRE warm DRAM tier before spilling
                # it right back out
                self._stats.add("hits_disk")
                return page
            # promote: disk -> DRAM (the file is dropped; DRAM is now
            # the authoritative copy and may re-demote later)
            self._disk.pop(chain, None)
            self._disk_bytes -= nbytes
            self._remove_file(path)
            self._dram[chain] = page
            self._dram_bytes += page.nbytes
            self._stats.add("hits_disk")
            self._stats.add("promotions")
            self._emit("promote", chain=chain_hex(chain),
                       bytes=page.nbytes)
            self._enforce_dram()
            self._sync_memory()
            return page

    def tier_of(self, chain) -> Optional[str]:
        """Which tier holds ``chain`` right now (``"dram"``/``"disk"``/
        None) — a PURE read: no LRU touch, no promotion.  The routing
        plane's primitive (the gateway's prefix-affinity read is
        documented as side-effect-free)."""
        with self._lock:
            if chain in self._dram:
                return "dram"
            if chain in self._disk:
                return "disk"
            return None

    def index(self) -> Dict[Any, str]:
        """``{chain: tier}`` over every resident page — the engine's
        ``prefix_index()`` merges this under its HBM entries."""
        with self._lock:
            out = {chain: "dram" for chain in self._dram}
            for chain in self._disk:
                out.setdefault(chain, "disk")
            return out

    def drop(self, chain) -> bool:
        """Remove one page from every tier; True when anything was
        resident."""
        with self._lock:
            page = self._dram.pop(chain, None)
            if page is not None:
                self._dram_bytes -= page.nbytes
            had_disk = chain in self._disk
            self._drop_disk(chain)
            self._sync_memory()
            return page is not None or had_disk

    # -------------------------------------------------------- telemetry --

    def _emit(self, what: str, **fields):
        if self.tracer is None:
            return
        self.tracer.emit("kvstore", what=what, **fields)

    def _sync_memory(self):
        """Mirror the tier byte totals into the active memory ledger
        (``telemetry_memory``): every tier transition resyncs the
        ``kv_pages`` host pool and its dram/disk tier counters as
        absolute values, so the ledger cannot drift from the store's own
        accounting.  One attribute check when no ledger is active."""
        ml = current_memory_ledger()
        if ml is None:
            return
        ml.set_bytes("kv_pages", self._dram_bytes, space="host",
                     tier="dram")
        ml.set_bytes("kv_pages", self._disk_bytes, space="host",
                     tier="disk")

    def counters(self) -> Dict[str, float]:
        return dict(self._stats.snapshot())

    def hit_rate(self) -> Optional[float]:
        """Lower-tier hit rate: (dram + disk hits) / lookups; None
        before the first lookup."""
        s = self._stats
        hits = float(s.value("hits_dram")) + float(s.value("hits_disk"))
        total = hits + float(s.value("misses")) \
            + float(s.value("meta_mismatches"))
        return None if total == 0 else hits / total

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able live view — what ``GET /kvstore`` serves."""
        with self._lock:
            out = {
                "dram": {"pages": len(self._dram),
                         "bytes": self._dram_bytes,
                         "capacity_bytes": self.dram_capacity_bytes},
                "disk": {"pages": len(self._disk),
                         "bytes": self._disk_bytes,
                         "capacity_bytes": self.disk_capacity_bytes,
                         "dir": self.disk_dir},
            }
        out["counters"] = self.counters()
        out["hit_rate"] = self.hit_rate()
        return out

    def metrics(self) -> Dict[str, float]:
        out = self.counters()
        with self._lock:
            out["dram_pages"] = float(len(self._dram))
            out["dram_bytes"] = float(self._dram_bytes)
            out["disk_pages"] = float(len(self._disk))
            out["disk_bytes"] = float(self._disk_bytes)
        return out

    def prometheus_text(self, namespace: str = "paddle_tpu_kvstore") -> str:
        with self._lock:
            gauges = {"dram_pages": len(self._dram),
                      "dram_bytes": self._dram_bytes,
                      "disk_pages": len(self._disk),
                      "disk_bytes": self._disk_bytes}
        hr = self.hit_rate()
        if hr is not None:
            gauges["hit_rate"] = hr
        return _prometheus_text(self._stats, namespace=namespace,
                                extra_gauges=gauges)

    def __repr__(self):
        with self._lock:
            return (f"TieredKVStore(dram={len(self._dram)}p/"
                    f"{self._dram_bytes}B, disk={len(self._disk)}p/"
                    f"{self._disk_bytes}B)")


class PageMigration:
    """Budgeted page-transfer schedule (module docstring): ``advance()``
    once per scheduler tick returns the pages that finished transferring
    under ``bytes_per_tick`` (None = unbounded — everything in one
    tick).  A page wider than the budget spans multiple ticks (the
    partial progress is tracked in bytes); delivery is page-granular, so
    a consumer never sees half a page.  ``restart()`` rewinds the whole
    schedule for a fresh destination — pages live host-side in the plan,
    so resuming after a destination quarantine re-delivers everything
    (correctness over cleverness: the fallback is recompute, never a
    torn page)."""

    def __init__(self, pages: Iterable[KVPage],
                 bytes_per_tick: Optional[int] = None):
        self.pages: List[KVPage] = list(pages)
        if bytes_per_tick is not None and int(bytes_per_tick) < 1:
            raise ValueError("bytes_per_tick must be >= 1 (or None)")
        self.bytes_per_tick = (None if bytes_per_tick is None
                               else int(bytes_per_tick))
        self.total_bytes = sum(p.nbytes for p in self.pages)
        self._next = 0          # first undelivered page
        self._partial = 0       # bytes already moved of pages[_next]
        self.transferred_bytes = 0
        self.ticks = 0

    @property
    def done(self) -> bool:
        return self._next >= len(self.pages)

    @property
    def remaining_bytes(self) -> int:
        return self.total_bytes - self.transferred_bytes

    def advance(self) -> List[KVPage]:
        """One tick of transfer; returns pages that COMPLETED this tick
        (possibly empty while a wide page is mid-flight)."""
        if self.done:
            return []
        self.ticks += 1
        budget = (float("inf") if self.bytes_per_tick is None
                  else self.bytes_per_tick)
        delivered: List[KVPage] = []
        while self._next < len(self.pages) and budget > 0:
            page = self.pages[self._next]
            left = page.nbytes - self._partial
            step = min(left, budget)
            self._partial += step
            self.transferred_bytes += step
            budget -= step
            if self._partial >= page.nbytes:     # covers zero-byte pages
                delivered.append(page)
                self._next += 1
                self._partial = 0
            else:
                break           # budget exhausted mid-page
        return delivered

    def restart(self):
        """Rewind for a new destination (resumable-on-quarantine)."""
        self._next = 0
        self._partial = 0
        self.transferred_bytes = 0

    def to_dict(self) -> Dict[str, Any]:
        return {"pages": len(self.pages), "delivered": self._next,
                "total_bytes": self.total_bytes,
                "transferred_bytes": self.transferred_bytes,
                "ticks": self.ticks,
                "bytes_per_tick": self.bytes_per_tick}

    def __repr__(self):
        return (f"PageMigration({self._next}/{len(self.pages)} pages, "
                f"{self.transferred_bytes}/{self.total_bytes}B)")
