"""Probability distributions (reference: python/paddle/distribution.py).

The reference builds every distribution out of eager elementwise ops plus
``uniform_random``/``gaussian_random`` kernels; here each distribution is a
thin object whose methods are pure jnp functions drawing from the framework
RNG streams (core/rng.py), so they trace cleanly under jit and run on the
MXU-free VPU path.  API parity: Distribution / Uniform / Normal /
Categorical (reference __all__, distribution.py:39) plus Bernoulli and a
``kl_divergence`` registry (later reference versions ship both).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .core import rng
from .core.tensor import Tensor, apply

__all__ = ["Distribution", "Uniform", "Normal", "Categorical", "Bernoulli",
           "kl_divergence", "register_kl"]


def _to_array(x, dtype=jnp.float32):
    if isinstance(x, Tensor):
        return x._data.astype(dtype)
    return jnp.asarray(x, dtype)


def _shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, (list, tuple)):
        return tuple(int(s) for s in shape)
    return (int(shape),)


class Distribution:
    """Abstract base: sample / entropy / log_prob / probs / kl_divergence."""

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Uniform(Distribution):
    """U[low, high) with reparameterized sampling.

    log_prob/probs follow the reference semantics (distribution.py:169):
    density 1/(high-low) inside the support, 0 outside.
    """

    def __init__(self, low, high, name=None):
        self.low = _to_array(low)
        self.high = _to_array(high)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(self.low.shape, self.high.shape)

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shape = _shape_tuple(shape) + self._batch
        u = jax.random.uniform(rng.next_key(), shape, jnp.float32)
        return Tensor(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            lp = -jnp.log(hi - lo)
            return jnp.where(inside, lp, -jnp.inf)
        return apply(f, value, Tensor(self.low), Tensor(self.high))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Normal(Distribution):
    """N(loc, scale^2) with reparameterized sampling."""

    def __init__(self, loc, scale, name=None):
        self.loc = _to_array(loc)
        self.scale = _to_array(scale)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(self.loc.shape, self.scale.shape)

    @property
    def mean(self):
        return Tensor(self.loc * jnp.ones(self._batch))

    @property
    def variance(self):
        return Tensor(self.scale * self.scale * jnp.ones(self._batch))

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shape = _shape_tuple(shape) + self._batch
        eps = jax.random.normal(rng.next_key(), shape, jnp.float32)
        return Tensor(self.loc + eps * self.scale)

    def log_prob(self, value):
        def f(v, mu, sigma):
            var = sigma * sigma
            return (-((v - mu) ** 2) / (2.0 * var)
                    - jnp.log(sigma) - 0.5 * math.log(2.0 * math.pi))
        return apply(f, value, Tensor(self.loc), Tensor(self.scale))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2.0 * math.pi)
                      + jnp.log(self.scale * jnp.ones(self._batch)))


class Categorical(Distribution):
    """Categorical over the last axis of ``logits``."""

    def __init__(self, logits, name=None):
        self.logits = _to_array(logits)

    @property
    def _log_pmf(self):
        return jax.nn.log_softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        shape = _shape_tuple(shape)
        out = jax.random.categorical(
            rng.next_key(), self.logits, axis=-1,
            shape=shape + self.logits.shape[:-1])
        return Tensor(out)

    def entropy(self):
        lp = self._log_pmf
        return Tensor(-jnp.sum(jnp.exp(lp) * lp, axis=-1))

    def log_prob(self, value):
        def f(v, logits):
            lp = jax.nn.log_softmax(logits, axis=-1)
            v = v.astype(jnp.int32)
            bshape = jnp.broadcast_shapes(v.shape, lp.shape[:-1])
            lpb = jnp.broadcast_to(lp, bshape + lp.shape[-1:])
            vb = jnp.broadcast_to(v, bshape)
            return jnp.take_along_axis(lpb, vb[..., None], axis=-1)[..., 0]
        return apply(f, value, Tensor(self.logits))

    def probs(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def kl_divergence(self, other):
        def f(p_logits, q_logits):
            p_lp = jax.nn.log_softmax(p_logits, axis=-1)
            q_lp = jax.nn.log_softmax(q_logits, axis=-1)
            return jnp.sum(jnp.exp(p_lp) * (p_lp - q_lp), axis=-1)
        return apply(f, Tensor(self.logits), Tensor(other.logits))


class Bernoulli(Distribution):
    """Bernoulli(probs) over {0, 1}."""

    def __init__(self, probs, name=None):
        self.probs_ = _to_array(probs)

    @property
    def mean(self):
        return Tensor(self.probs_)

    @property
    def variance(self):
        return Tensor(self.probs_ * (1.0 - self.probs_))

    def sample(self, shape=()):
        shape = _shape_tuple(shape) + self.probs_.shape
        out = jax.random.bernoulli(rng.next_key(), self.probs_, shape)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def f(v, p):
            p = jnp.clip(p, 1e-7, 1.0 - 1e-7)
            return v * jnp.log(p) + (1.0 - v) * jnp.log1p(-p)
        return apply(f, value, Tensor(self.probs_))

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1.0 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1.0 - p) * jnp.log1p(-p)))


# --------------------------------------------------------------------------
# KL divergence registry (reference pattern: paddle.distribution.kl.register_kl)
# --------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        for (tp, tq), cand in _KL_REGISTRY.items():
            if isinstance(p, tp) and isinstance(q, tq):
                fn = cand
                break
    if fn is None:
        raise NotImplementedError(
            f"KL divergence not registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    inside = (q.low <= p.low) & (p.high <= q.high)
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return Tensor(jnp.where(inside, kl, jnp.inf))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    return p.kl_divergence(q)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    pp = jnp.clip(p.probs_, 1e-7, 1.0 - 1e-7)
    qq = jnp.clip(q.probs_, 1e-7, 1.0 - 1e-7)
    return Tensor(pp * (jnp.log(pp) - jnp.log(qq))
                  + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))
