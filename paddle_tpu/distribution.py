"""Probability distributions (reference: python/paddle/distribution.py).

The reference builds every distribution out of eager elementwise ops plus
``uniform_random``/``gaussian_random`` kernels; here each distribution is a
thin object whose methods are pure jnp functions drawing from the framework
RNG streams (core/rng.py), so they trace cleanly under jit and run on the
MXU-free VPU path.  API parity: Distribution / Uniform / Normal /
Categorical (reference __all__, distribution.py:39) plus Bernoulli and a
``kl_divergence`` registry (later reference versions ship both).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .core import rng
from .core.tensor import Tensor, apply

__all__ = ["Distribution", "Uniform", "Normal", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "Multinomial", "Gamma", "LogNormal",
           "Laplace", "Independent", "TransformedDistribution",
           "Transform", "AffineTransform", "ExpTransform", "PowerTransform",
           "SigmoidTransform", "TanhTransform", "ChainTransform",
           "kl_divergence", "register_kl"]


def _to_array(x, dtype=jnp.float32):
    if isinstance(x, Tensor):
        return x._data.astype(dtype)
    return jnp.asarray(x, dtype)


def _shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, (list, tuple)):
        return tuple(int(s) for s in shape)
    return (int(shape),)


class Distribution:
    """Abstract base: sample / entropy / log_prob / probs / kl_divergence."""

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Uniform(Distribution):
    """U[low, high) with reparameterized sampling.

    log_prob/probs follow the reference semantics (distribution.py:169):
    density 1/(high-low) inside the support, 0 outside.
    """

    def __init__(self, low, high, name=None):
        self.low = _to_array(low)
        self.high = _to_array(high)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(self.low.shape, self.high.shape)

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shape = _shape_tuple(shape) + self._batch
        u = jax.random.uniform(rng.next_key(), shape, jnp.float32)
        return Tensor(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            lp = -jnp.log(hi - lo)
            return jnp.where(inside, lp, -jnp.inf)
        return apply(f, value, Tensor(self.low), Tensor(self.high))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Normal(Distribution):
    """N(loc, scale^2) with reparameterized sampling."""

    def __init__(self, loc, scale, name=None):
        self.loc = _to_array(loc)
        self.scale = _to_array(scale)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(self.loc.shape, self.scale.shape)

    @property
    def mean(self):
        return Tensor(self.loc * jnp.ones(self._batch))

    @property
    def variance(self):
        return Tensor(self.scale * self.scale * jnp.ones(self._batch))

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shape = _shape_tuple(shape) + self._batch
        eps = jax.random.normal(rng.next_key(), shape, jnp.float32)
        return Tensor(self.loc + eps * self.scale)

    def log_prob(self, value):
        def f(v, mu, sigma):
            var = sigma * sigma
            return (-((v - mu) ** 2) / (2.0 * var)
                    - jnp.log(sigma) - 0.5 * math.log(2.0 * math.pi))
        return apply(f, value, Tensor(self.loc), Tensor(self.scale))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2.0 * math.pi)
                      + jnp.log(self.scale * jnp.ones(self._batch)))


class Categorical(Distribution):
    """Categorical over the last axis of ``logits``."""

    def __init__(self, logits, name=None):
        self.logits = _to_array(logits)

    @property
    def _log_pmf(self):
        return jax.nn.log_softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        shape = _shape_tuple(shape)
        out = jax.random.categorical(
            rng.next_key(), self.logits, axis=-1,
            shape=shape + self.logits.shape[:-1])
        return Tensor(out)

    def entropy(self):
        lp = self._log_pmf
        return Tensor(-jnp.sum(jnp.exp(lp) * lp, axis=-1))

    def log_prob(self, value):
        def f(v, logits):
            lp = jax.nn.log_softmax(logits, axis=-1)
            v = v.astype(jnp.int32)
            bshape = jnp.broadcast_shapes(v.shape, lp.shape[:-1])
            lpb = jnp.broadcast_to(lp, bshape + lp.shape[-1:])
            vb = jnp.broadcast_to(v, bshape)
            return jnp.take_along_axis(lpb, vb[..., None], axis=-1)[..., 0]
        return apply(f, value, Tensor(self.logits))

    def probs(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def kl_divergence(self, other):
        def f(p_logits, q_logits):
            p_lp = jax.nn.log_softmax(p_logits, axis=-1)
            q_lp = jax.nn.log_softmax(q_logits, axis=-1)
            return jnp.sum(jnp.exp(p_lp) * (p_lp - q_lp), axis=-1)
        return apply(f, Tensor(self.logits), Tensor(other.logits))


class Bernoulli(Distribution):
    """Bernoulli(probs) over {0, 1}."""

    def __init__(self, probs, name=None):
        self.probs_ = _to_array(probs)

    @property
    def mean(self):
        return Tensor(self.probs_)

    @property
    def variance(self):
        return Tensor(self.probs_ * (1.0 - self.probs_))

    def sample(self, shape=()):
        shape = _shape_tuple(shape) + self.probs_.shape
        out = jax.random.bernoulli(rng.next_key(), self.probs_, shape)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def f(v, p):
            p = jnp.clip(p, 1e-7, 1.0 - 1e-7)
            return v * jnp.log(p) + (1.0 - v) * jnp.log1p(-p)
        return apply(f, value, Tensor(self.probs_))

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1.0 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1.0 - p) * jnp.log1p(-p)))


class Gamma(Distribution):
    """Gamma(concentration, rate) — density r^c x^{c-1} e^{-rx} / Γ(c).

    Reference: python/paddle/distribution/gamma.py.  Sampling uses
    ``jax.random.gamma`` (reparameterized via implicit differentiation, so
    ``rsample`` gradients flow to ``concentration``)."""

    def __init__(self, concentration, rate, name=None):
        self.concentration = _to_array(concentration)
        self.rate = _to_array(rate)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(self.concentration.shape, self.rate.shape)

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate * jnp.ones(self._batch))

    @property
    def variance(self):
        return Tensor(self.concentration / self.rate ** 2
                      * jnp.ones(self._batch))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shape = _shape_tuple(shape) + self._batch
        g = jax.random.gamma(rng.next_key(),
                             jnp.broadcast_to(self.concentration, shape))
        return Tensor(g / self.rate)

    def log_prob(self, value):
        def f(v, c, r):
            return (c * jnp.log(r) + (c - 1.0) * jnp.log(v) - r * v
                    - jax.scipy.special.gammaln(c))
        return apply(f, value, Tensor(self.concentration), Tensor(self.rate))

    def entropy(self):
        c, r = jnp.broadcast_arrays(self.concentration, self.rate)
        dg = jax.scipy.special.digamma(c)
        return Tensor(c - jnp.log(r) + jax.scipy.special.gammaln(c)
                      + (1.0 - c) * dg)


class Beta(Distribution):
    """Beta(alpha, beta) on (0, 1).

    Reference: python/paddle/distribution/beta.py (dirichlet-backed there
    too).  Sampling composes two reparameterized gammas: X = Ga/(Ga+Gb)."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _to_array(alpha)
        self.beta = _to_array(beta)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(self.alpha.shape, self.beta.shape)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta)
                      * jnp.ones(self._batch))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s * s * (s + 1.0))
                      * jnp.ones(self._batch))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shape = _shape_tuple(shape) + self._batch
        ga = jax.random.gamma(rng.next_key(),
                              jnp.broadcast_to(self.alpha, shape))
        gb = jax.random.gamma(rng.next_key(),
                              jnp.broadcast_to(self.beta, shape))
        return Tensor(ga / (ga + gb))

    def log_prob(self, value):
        def f(v, a, b):
            return ((a - 1.0) * jnp.log(v) + (b - 1.0) * jnp.log1p(-v)
                    - jax.scipy.special.betaln(a, b))
        return apply(f, value, Tensor(self.alpha), Tensor(self.beta))

    def entropy(self):
        a, b = jnp.broadcast_arrays(self.alpha, self.beta)
        dg = jax.scipy.special.digamma
        return Tensor(jax.scipy.special.betaln(a, b)
                      - (a - 1.0) * dg(a) - (b - 1.0) * dg(b)
                      + (a + b - 2.0) * dg(a + b))


class Dirichlet(Distribution):
    """Dirichlet(concentration) over the simplex (last axis).

    Reference: python/paddle/distribution/dirichlet.py."""

    def __init__(self, concentration, name=None):
        self.concentration = _to_array(concentration)

    @property
    def mean(self):
        c = self.concentration
        return Tensor(c / jnp.sum(c, axis=-1, keepdims=True))

    @property
    def variance(self):
        c = self.concentration
        c0 = jnp.sum(c, axis=-1, keepdims=True)
        m = c / c0
        return Tensor(m * (1.0 - m) / (c0 + 1.0))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shape = _shape_tuple(shape) + self.concentration.shape[:-1]
        out = jax.random.dirichlet(rng.next_key(), self.concentration, shape)
        return Tensor(out)

    def log_prob(self, value):
        def f(v, c):
            norm = (jnp.sum(jax.scipy.special.gammaln(c), axis=-1)
                    - jax.scipy.special.gammaln(jnp.sum(c, axis=-1)))
            return jnp.sum((c - 1.0) * jnp.log(v), axis=-1) - norm
        return apply(f, value, Tensor(self.concentration))

    def entropy(self):
        c = self.concentration
        c0 = jnp.sum(c, axis=-1)
        k = c.shape[-1]
        dg = jax.scipy.special.digamma
        lnB = (jnp.sum(jax.scipy.special.gammaln(c), axis=-1)
               - jax.scipy.special.gammaln(c0))
        return Tensor(lnB + (c0 - k) * dg(c0)
                      - jnp.sum((c - 1.0) * dg(c), axis=-1))


class Multinomial(Distribution):
    """Multinomial(total_count, probs) — counts over the last axis.

    Reference: python/paddle/distribution/multinomial.py.  ``total_count``
    is static (a trace-time int), matching the reference's int argument."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        p = _to_array(probs)
        self.probs_ = p / jnp.sum(p, axis=-1, keepdims=True)

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs_)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs_ * (1.0 - self.probs_))

    def sample(self, shape=()):
        shape = _shape_tuple(shape) + self.probs_.shape[:-1]
        n = jnp.full(shape, self.total_count, jnp.float32)
        out = jax.random.multinomial(
            rng.next_key(), n, jnp.broadcast_to(
                self.probs_, shape + self.probs_.shape[-1:]))
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def f(v, p):
            gl = jax.scipy.special.gammaln
            coeff = gl(jnp.sum(v, axis=-1) + 1.0) - jnp.sum(gl(v + 1.0),
                                                            axis=-1)
            return coeff + jnp.sum(
                jnp.where(v == 0, 0.0, v * jnp.log(p)), axis=-1)
        return apply(f, value, Tensor(self.probs_))


# --------------------------------------------------------------------------
# Transforms + TransformedDistribution
# (reference: python/paddle/distribution/transform.py — AffineTransform,
#  ExpTransform, SigmoidTransform, TanhTransform, PowerTransform,
#  ChainTransform — and transformed_distribution.py)
# --------------------------------------------------------------------------

class Transform:
    """Bijection y = forward(x) with log|det J| tracked elementwise."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = _to_array(loc)
        self.scale = _to_array(scale)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class ExpTransform(Transform):
    """y = exp(x)."""

    def forward(self, x):
        return jnp.exp(x)

    def inverse(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power (x > 0)."""

    def __init__(self, power):
        self.power = _to_array(power)

    def forward(self, x):
        return x ** self.power

    def inverse(self, y):
        return y ** (1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * x ** (self.power - 1.0)))


class SigmoidTransform(Transform):
    """y = sigmoid(x) ∈ (0, 1)."""

    def forward(self, x):
        return jax.nn.sigmoid(x)

    def inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        # log σ'(x) = -softplus(-x) - softplus(x)
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    """y = tanh(x) ∈ (-1, 1)."""

    def forward(self, x):
        return jnp.tanh(x)

    def inverse(self, y):
        return jnp.arctanh(y)

    def forward_log_det_jacobian(self, x):
        # log(1 - tanh²x) = 2(log2 - x - softplus(-2x)) — stable form
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class ChainTransform(Transform):
    """Composition t_n ∘ … ∘ t_1 (first transform applied first)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t.forward_log_det_jacobian(x)
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    """base distribution pushed through a (chain of) transform(s).

    Reference: python/paddle/distribution/transformed_distribution.py —
    log_prob(y) = base.log_prob(t⁻¹(y)) + log|det J_{t⁻¹}|(y)."""

    def __init__(self, base, transforms, name=None):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = ChainTransform(list(transforms))

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return Tensor(self.transform.forward(_to_array(x)))

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return Tensor(self.transform.forward(_to_array(x)))

    def log_prob(self, value):
        y = _to_array(value)
        x = self.transform.inverse(y)
        base_lp = _to_array(self.base.log_prob(Tensor(x)))
        # equivalent to + inverse_log_det_jacobian(y), but reuses the x we
        # already inverted instead of inverting the whole chain again
        return Tensor(base_lp - self.transform.forward_log_det_jacobian(x))


class LogNormal(TransformedDistribution):
    """exp(N(loc, scale²)) — the canonical TransformedDistribution.

    Reference: python/paddle/distribution/lognormal.py (Normal + ExpTransform
    there as well)."""

    def __init__(self, loc, scale, name=None):
        super().__init__(Normal(loc, scale), ExpTransform())
        self.loc = _to_array(loc)
        self.scale = _to_array(scale)

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + 0.5 * self.scale ** 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return Tensor((jnp.exp(s2) - 1.0) * jnp.exp(2.0 * self.loc + s2))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2.0 * math.pi)
                      + jnp.log(self.scale) + self.loc)


class Laplace(Distribution):
    """Laplace(loc, scale).  Reference: python/paddle/distribution/laplace.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _to_array(loc)
        self.scale = _to_array(scale)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(self.loc.shape, self.scale.shape)

    @property
    def mean(self):
        return Tensor(self.loc * jnp.ones(self._batch))

    @property
    def variance(self):
        return Tensor(2.0 * self.scale ** 2 * jnp.ones(self._batch))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shape = _shape_tuple(shape) + self._batch
        u = jax.random.uniform(rng.next_key(), shape, jnp.float32,
                               minval=-0.5, maxval=0.5)
        # minval is inclusive: u = -0.5 would give log1p(-1) = -inf; pull
        # the endpoint in by one ulp-scale step (same guard torch uses)
        u = jnp.clip(u, -0.5 + 1e-7, 0.5 - 1e-7)
        return Tensor(self.loc
                      - self.scale * jnp.sign(u) * jnp.log1p(-2.0 * jnp.abs(u)))

    def log_prob(self, value):
        def f(v, mu, b):
            return -jnp.abs(v - mu) / b - jnp.log(2.0 * b)
        return apply(f, value, Tensor(self.loc), Tensor(self.scale))

    def entropy(self):
        return Tensor(1.0 + jnp.log(2.0 * self.scale * jnp.ones(self._batch)))


class Independent(Distribution):
    """Reinterpret the last ``reinterpreted_batch_rank`` batch dims as event
    dims: log_prob sums over them.  Reference: python/paddle/distribution/
    independent.py."""

    def __init__(self, base, reinterpreted_batch_rank=1, name=None):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = _to_array(self.base.log_prob(value))
        return Tensor(jnp.sum(lp, axis=tuple(range(-self.rank, 0))))

    def entropy(self):
        ent = _to_array(self.base.entropy())
        return Tensor(jnp.sum(ent, axis=tuple(range(-self.rank, 0))))


# --------------------------------------------------------------------------
# KL divergence registry (reference pattern: paddle.distribution.kl.register_kl)
# --------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        for (tp, tq), cand in _KL_REGISTRY.items():
            if isinstance(p, tp) and isinstance(q, tq):
                fn = cand
                break
    if fn is None:
        raise NotImplementedError(
            f"KL divergence not registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    inside = (q.low <= p.low) & (p.high <= q.high)
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return Tensor(jnp.where(inside, kl, jnp.inf))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    return p.kl_divergence(q)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    pp = jnp.clip(p.probs_, 1e-7, 1.0 - 1e-7)
    qq = jnp.clip(q.probs_, 1e-7, 1.0 - 1e-7)
    return Tensor(pp * (jnp.log(pp) - jnp.log(qq))
                  + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    dg = jax.scipy.special.digamma
    a1, b1 = jnp.broadcast_arrays(p.alpha, p.beta)
    a2, b2 = q.alpha, q.beta
    s1 = a1 + b1
    return Tensor(jax.scipy.special.betaln(a2, b2)
                  - jax.scipy.special.betaln(a1, b1)
                  + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                  + (a2 - a1 + b2 - b1) * dg(s1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    dg = jax.scipy.special.digamma
    gl = jax.scipy.special.gammaln
    c1, c2 = p.concentration, q.concentration
    s1 = jnp.sum(c1, axis=-1)
    return Tensor(gl(s1) - jnp.sum(gl(c1), axis=-1)
                  - gl(jnp.sum(c2, axis=-1)) + jnp.sum(gl(c2), axis=-1)
                  + jnp.sum((c1 - c2) * (dg(c1) - dg(s1)[..., None]),
                            axis=-1))


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    dg = jax.scipy.special.digamma
    gl = jax.scipy.special.gammaln
    c1, r1 = jnp.broadcast_arrays(p.concentration, p.rate)
    c2, r2 = q.concentration, q.rate
    return Tensor((c1 - c2) * dg(c1) - gl(c1) + gl(c2)
                  + c2 * (jnp.log(r1) - jnp.log(r2))
                  + c1 * (r2 - r1) / r1)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    b1, b2 = p.scale, q.scale
    ad = jnp.abs(p.loc - q.loc)
    return Tensor(jnp.log(b2 / b1)
                  + (b1 * jnp.exp(-ad / b1) + ad) / b2 - 1.0)


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    # KL is invariant under the shared exp bijection: reduce to the bases
    return _kl_normal_normal(Normal(p.loc, p.scale), Normal(q.loc, q.scale))
