"""Model hub (reference: python/paddle/hub.py — list/help/load over a
hubconf.py).  Zero-egress build: only ``source="local"`` works; github/gitee
sources raise with a pointer to a local checkout.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} found under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source: str, repo_dir: str) -> str:
    if source != "local":
        raise ValueError(
            f"source={source!r} needs network access, which this zero-egress "
            f"build does not have; clone the repo and use source='local'")
    return repo_dir


def list(repo_dir: str, source: str = "local", force_reload: bool = False
         ) -> List[str]:
    """Entrypoint names exported by the repo's hubconf (reference hub.list)."""
    mod = _load_hubconf(_check_source(source, repo_dir))
    return sorted(n for n in dir(mod)
                  if callable(getattr(mod, n)) and not n.startswith("_"))


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False) -> str:
    mod = _load_hubconf(_check_source(source, repo_dir))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"hubconf has no entrypoint {model!r}; "
                         f"available: {list(repo_dir)}")
    return fn.__doc__ or ""


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    mod = _load_hubconf(_check_source(source, repo_dir))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"hubconf has no entrypoint {model!r}; "
                         f"available: {list(repo_dir)}")
    return fn(**kwargs)
