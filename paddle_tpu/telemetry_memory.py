"""Memory ledger: exhaustive byte attribution for device and host pools.

The goodput ledger (``telemetry_ledger.RunLedger``) answers *where did the
wall clock go*; nothing answered *where did the bytes go*.  The system
makes byte claims it could not measure — weight-update sharding pins a
">=1.8x opt-HBM reduction" analytically (arXiv:2004.13336), the tiered KV
store migrates pages HBM → DRAM → disk with only page-count telemetry —
and the next scale-out tier (MPMD, multi-host transport) will debug OOMs
blind without live/peak bytes per pool.  :class:`MemoryLedger` partitions
bytes the way the goodput ledger partitions seconds:

==========================  ==============================================
pool                        bytes held by …
==========================  ==============================================
``params``                  model parameter trees (incl. buffers — BN
                            stats ride the model, not the optimizer)
``optimizer_state``         optimizer slot trees / fused flat shards
                            (incl. AMP scaler state)
``grads_comm_buffers``      gradient / collective staging state (EF
                            residuals, comm buffers)
``kv_pages``                paged-attention KV: per tier — ``hbm``
                            (device-resident caches), ``dram`` / ``disk``
                            (the TieredKVStore's host tiers)
``executables``             serialized compiled programs (the AOT
                            executable cache's blobs — a host-side proxy
                            for device code size)
``activations_workspace``   live intermediates registered explicitly by a
                            harness (activation stashes, microbatch
                            workspace)
``other``                   the residual — live arrays nothing registered
==========================  ==============================================

Two spaces, two source kinds:

- **device**: refreshed by :meth:`MemoryLedger.census` — ONE
  ``jax.live_arrays()`` walk classifying every live array by identity
  against the registered pytrees (trainers register state through
  ``register_train_state``; engines through ``attach_memory``), with
  addressable-shard bytes (what devices actually hold: a replicated array
  on R devices costs R×, a 1/R shard costs 1×) and per-device totals.
  The residual lands in ``other`` — the conservation invariant is
  ``sum(pool device bytes) == census total`` by construction, with
  over/under-registration *visible*, never silently clipped.
- **host**: event-driven ``account()`` deltas at the allocation sites
  (``kv_store`` tier transitions, the AOT cache's blob writes), mirrored
  per KV tier.

Peaks are ``set_max``-style watermarks (global per space and per pool);
every new watermark appends to a bounded ring and, with a tracer
attached, emits a ``memory`` event — so an OOM's approach survives in the
flight recorder.  :meth:`forensics` is the OOM post-mortem payload (top
pools, recent growth, largest arrays with tree paths, allocator stats);
``FlightRecorder`` writes it as a ``*-forensics.json`` section beside the
regular dump.

This module is the **single accounting point** for raw memory
introspection: ``jax.live_arrays()`` and PJRT ``memory_stats()`` calls
anywhere else are tpulint findings (``raw-memory-introspection``), the
same authority pattern as ``sharding_rules`` for ``PartitionSpec``.
Everything is zero-cost when no ledger is active: one ``is None`` check
per seam (:func:`current_memory_ledger` / :func:`account_bytes`).
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["MemoryLedger", "POOLS", "SPACES", "KV_TIERS",
           "set_active_memory_ledger", "current_memory_ledger",
           "account_bytes", "live_array_census", "device_allocator_stats",
           "chrome_counters_from_memory_dump"]

#: The exhaustive pool taxonomy, in display order.  ``other`` is the
#: census residual — live arrays nothing registered — never written to
#: directly.
POOLS: Tuple[str, ...] = (
    "params", "optimizer_state", "grads_comm_buffers", "kv_pages",
    "executables", "activations_workspace", "other")

SPACES: Tuple[str, ...] = ("device", "host")

#: KV page tiers (kv_store.py's ladder): ``hbm`` is device space, the
#: host tiers mirror the TieredKVStore's DRAM/disk byte counters.
KV_TIERS: Tuple[str, ...] = ("hbm", "dram", "disk")

#: state-dict key → pool, for ``register_train_state`` (the trainer
#: builders' ``state0`` layout: jit/functional.py, distributed/*).
_STATE_KEY_POOL = {"params": "params", "buffers": "params",
                   "opt": "optimizer_state", "scaler": "optimizer_state",
                   "comm_e": "grads_comm_buffers"}

#: how many largest-array rows a census retains for forensics
_TOP_ARRAYS = 8


def _leaf_bytes(leaf) -> int:
    """Logical bytes of one array-like leaf (size × itemsize; sharded
    arrays count their global shape — the addressable view is computed
    separately in the census)."""
    import numpy as np
    if not hasattr(leaf, "dtype"):
        return 0
    item = np.dtype(leaf.dtype).itemsize
    shape = getattr(leaf, "shape", ())
    return int(np.prod(shape)) * item if shape else item


def _addressable_bytes(arr) -> int:
    """Bytes this process's devices actually hold for ``arr``: the sum of
    addressable shard bytes (replicated on R devices → R× logical; a 1/R
    shard → 1× logical).  Falls back to logical bytes for arrays without
    a shard view (committed single-device, numpy)."""
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        return _leaf_bytes(arr)
    total = 0
    for sh in shards:
        data = getattr(sh, "data", None)
        total += _leaf_bytes(data) if data is not None else 0
    return total


def live_array_census(groups: Dict[str, Any]) -> Dict[str, int]:
    """One ``jax.live_arrays()`` walk classifying every live array by
    identity into the named groups (``{name: pytree}``); unmatched arrays
    land in ``other``.  Returns ``{<name>_bytes: ..., other_bytes: ...,
    total_bytes: ..., arrays: ...}`` in logical bytes — the shared
    classifier behind ``TrainMonitor.hbm_census`` and
    :meth:`MemoryLedger.census` (this module is the single accounting
    point for the raw walk)."""
    import jax

    ids: Dict[str, set] = {}
    for name, tree in groups.items():
        ids[name] = {id(l) for l in jax.tree_util.tree_leaves(tree)
                     if hasattr(l, "dtype")}
    counts = {f"{name}_bytes": 0 for name in groups}
    counts["other_bytes"] = 0
    n_arrays = 0
    for a in jax.live_arrays():
        if getattr(a, "is_deleted", lambda: False)():
            continue
        n_arrays += 1
        b = _leaf_bytes(a)
        for name, idset in ids.items():
            if id(a) in idset:
                counts[f"{name}_bytes"] += b
                break
        else:
            counts["other_bytes"] += b
    counts["total_bytes"] = sum(counts.values())
    counts["arrays"] = n_arrays
    return counts


def device_allocator_stats(device_index: int = 0) -> Dict[str, int]:
    """Per-device allocator stats from the PJRT client (≙ the reference's
    STAT_gpu0_mem_size family fed by the CUDA allocator).  THE authority
    for the raw ``memory_stats()`` call — ``utils.stats
    .device_memory_stats`` delegates here; calling it anywhere else is a
    tpulint finding.  Empty dict when the backend exposes nothing (CPU)."""
    import jax
    devs = jax.local_devices()
    if device_index >= len(devs):
        return {}
    stats = devs[device_index].memory_stats() or {}
    return {k: int(v) for k, v in stats.items()}


class MemoryLedger:
    """Exhaustive byte attribution across :data:`POOLS` (module
    docstring).  ``capacity`` bounds the retained ``(ts, space, pool,
    bytes)`` sample series (the chrome counter track / flight-recorder
    payload); ``ring`` bounds the watermark-crossing event ring.  All
    mutation is under one lock; ``account`` is a dict add — cheap enough
    for per-page kv seams, and seams only reach it when a ledger is
    active."""

    def __init__(self, capacity: int = 4096, ring: int = 256,
                 tracer=None, logger: Optional[logging.Logger] = None):
        if capacity < 1 or ring < 1:
            raise ValueError("capacity and ring must be >= 1")
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._bytes: Dict[Tuple[str, str], int] = {
            (s, p): 0 for s in SPACES for p in POOLS}
        self._peak: Dict[Tuple[str, str], int] = dict(self._bytes)
        self._peak_total: Dict[str, int] = {s: 0 for s in SPACES}
        self._kv_tiers: Dict[str, int] = {t: 0 for t in KV_TIERS}
        self._kv_tier_peak: Dict[str, int] = {t: 0 for t in KV_TIERS}
        self._trees: Dict[str, Dict[str, Any]] = {}   # name -> registration
        self._series: collections.deque = collections.deque(maxlen=capacity)
        self._ring: collections.deque = collections.deque(maxlen=ring)
        self._n_watermarks = 0
        self._n_census = 0
        self._largest: List[Dict[str, Any]] = []
        self._per_device: Dict[str, int] = {}
        self._census_meta: Optional[Dict[str, Any]] = None
        self._tracer = tracer
        self._prev_active: Optional["MemoryLedger"] = None
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)

    # ------------------------------------------------------------- clock --
    def now(self) -> float:
        return time.monotonic() - self._t0

    def set_tracer(self, tracer):
        """Attach a ``telemetry.Tracer``: watermark crossings emit
        ``memory`` events into its ring, so OOM approach survives in the
        flight recorder next to tick/compile spans."""
        self._tracer = tracer
        return self

    # ------------------------------------------------------ registration --
    def register_tree(self, pool: str, tree, name: Optional[str] = None,
                      ) -> str:
        """Register a pytree's leaves under ``pool`` for census
        classification (device space).  Re-registering a ``name`` replaces
        the previous registration — trainers whose donated state is
        rebuilt every step re-register the fresh tree (the
        ``instrument_train_step`` seam).  Returns the registration name."""
        if pool not in POOLS or pool == "other":
            raise ValueError(f"unknown pool {pool!r}; one of "
                             f"{[p for p in POOLS if p != 'other']}")
        import jax
        leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
        ids: Dict[int, Tuple[str, int]] = {}
        for path, leaf in leaves_with_path:
            if not hasattr(leaf, "dtype"):
                continue
            ids[id(leaf)] = (jax.tree_util.keystr(path), _leaf_bytes(leaf))
        name = name or f"{pool}{len(self._trees)}"
        with self._lock:
            self._trees[name] = {"pool": pool, "ids": ids}
        return name

    def unregister_tree(self, name: str) -> bool:
        with self._lock:
            return self._trees.pop(name, None) is not None

    def register_train_state(self, state: Dict[str, Any],
                             name: str = "train_state") -> str:
        """Register a trainer ``state`` dict by its conventional top-level
        keys (params/buffers → params, opt/scaler → optimizer_state,
        comm_e → grads_comm_buffers; unknown keys ride along as params'
        siblings are not invented — they stay unregistered and show up in
        ``other``, which is the honest place for state this table does
        not understand)."""
        buckets: Dict[str, list] = {}
        for key, sub in state.items():
            pool = _STATE_KEY_POOL.get(key)
            if pool is not None:
                buckets.setdefault(pool, []).append((key, sub))
        for pool, subs in buckets.items():
            self.register_tree(pool, dict(subs), name=f"{name}.{pool}")
        # drop pools this state no longer carries (a re-registered state
        # without comm_e must not leave stale ids classifying)
        with self._lock:
            stale = [n for n in self._trees
                     if n.startswith(f"{name}.") and
                     n.split(".", 1)[1] not in buckets]
            for n in stale:
                del self._trees[n]
        return name

    # ------------------------------------------------------------ ingest --
    def account(self, pool: str, delta: int, space: str = "host",
                tier: Optional[str] = None):
        """Attribute a byte delta to ``pool`` in ``space`` (the
        event-driven path: kv tier transitions, executable-cache blob
        writes).  ``tier`` additionally mirrors the delta onto a KV tier
        counter.  Negative deltas release; totals clamp at zero (a
        release crossing zero indicates a missed account and is logged
        once per ledger rather than going negative silently)."""
        if pool not in POOLS:
            raise ValueError(f"unknown pool {pool!r}; one of {POOLS}")
        if space not in SPACES:
            raise ValueError(f"unknown space {space!r}; one of {SPACES}")
        if tier is not None and tier not in KV_TIERS:
            raise ValueError(f"unknown kv tier {tier!r}; one of {KV_TIERS}")
        events = []
        with self._lock:
            key = (space, pool)
            new = self._bytes[key] + int(delta)
            if new < 0:
                self._log.warning(
                    "memory ledger: %s/%s released below zero (delta %d); "
                    "clamping — an allocation site is not accounting",
                    space, pool, delta)
                new = 0
            self._bytes[key] = new
            if tier is not None:
                t = max(0, self._kv_tiers[tier] + int(delta))
                self._kv_tiers[tier] = t
                if t > self._kv_tier_peak[tier]:
                    self._kv_tier_peak[tier] = t
            events = self._note_locked(space, pool, new)
        self._emit_events(events)

    def set_bytes(self, pool: str, value: int, space: str = "host",
                  tier: Optional[str] = None):
        """Absolute-value twin of :meth:`account` for sources that track
        their own totals (the kv store's tier counters on snapshot
        resync)."""
        with self._lock:
            cur = self._bytes[(space, pool)] if tier is None \
                else self._kv_tiers[tier]
        self.account(pool, int(value) - cur, space=space, tier=tier)

    def _note_locked(self, space: str, pool: str, total: int):
        """Record one sample and any watermark crossings (caller holds
        the lock).  Returns tracer events to emit outside the lock."""
        ts = time.monotonic() - self._t0
        self._series.append((ts, space, pool, total))
        events = []
        if total > self._peak[(space, pool)]:
            prev = self._peak[(space, pool)]
            self._peak[(space, pool)] = total
            self._n_watermarks += 1
            ev = {"ts": round(ts, 6), "space": space, "pool": pool,
                  "bytes": total, "prev_bytes": prev}
            self._ring.append(ev)
            events.append(ev)
        space_total = sum(v for (s, _p), v in self._bytes.items()
                          if s == space)
        if space_total > self._peak_total[space]:
            self._peak_total[space] = space_total
        return events

    def _emit_events(self, events):
        tr = self._tracer
        if tr is None or not events:
            return
        for ev in events:
            tr.emit("memory", what="watermark", **ev)

    # ------------------------------------------------------------ census --
    def census(self) -> Dict[str, Any]:
        """Refresh the device-space pools from ONE ``jax.live_arrays()``
        walk: every live array is classified by identity against the
        registered trees; the residual is ``other``.  Pool bytes are
        **addressable** (what this process's devices hold); ``logical``
        keeps the global-shape view beside it.  Also refreshes per-device
        totals and the largest-array forensics rows.  Conservation:
        ``sum(pools.values()) == total_bytes`` by construction."""
        import jax

        with self._lock:
            id_pool: Dict[int, Tuple[str, str]] = {}
            for reg in self._trees.values():
                pool = reg["pool"]
                for i, (path, _b) in reg["ids"].items():
                    id_pool[i] = (pool, path)
        pools = {p: 0 for p in POOLS}
        logical = {p: 0 for p in POOLS}
        per_device: Dict[str, int] = {}
        rows: List[Dict[str, Any]] = []
        n_arrays = 0
        for a in jax.live_arrays():
            if getattr(a, "is_deleted", lambda: False)():
                continue
            n_arrays += 1
            lb = _leaf_bytes(a)
            ab = _addressable_bytes(a)
            pool, path = id_pool.get(id(a), ("other", None))
            pools[pool] += ab
            logical[pool] += lb
            shards = getattr(a, "addressable_shards", None) or ()
            for sh in shards:
                dev = getattr(sh, "device", None)
                data = getattr(sh, "data", None)
                if dev is not None:
                    per_device[str(dev)] = per_device.get(str(dev), 0) \
                        + (_leaf_bytes(data) if data is not None else 0)
            rows.append({"pool": pool, "path": path, "bytes": ab,
                         "shape": list(getattr(a, "shape", ())),
                         "dtype": str(getattr(a, "dtype", "?"))})
        rows.sort(key=lambda r: -r["bytes"])
        total = sum(pools.values())
        events = []
        with self._lock:
            for p in POOLS:
                self._bytes[("device", p)] = pools[p]
                events.extend(self._note_locked("device", p, pools[p]))
            hbm_kv = pools["kv_pages"]
            self._kv_tiers["hbm"] = hbm_kv
            if hbm_kv > self._kv_tier_peak["hbm"]:
                self._kv_tier_peak["hbm"] = hbm_kv
            self._largest = rows[:_TOP_ARRAYS]
            self._per_device = per_device
            self._n_census += 1
            self._census_meta = {"ts": round(time.monotonic() - self._t0, 6),
                                 "arrays": n_arrays, "total_bytes": total,
                                 "other_bytes": pools["other"]}
        self._emit_events(events)
        census = {"pools": pools, "logical": logical,
                  "per_device": per_device, "total_bytes": total,
                  "logical_total_bytes": sum(logical.values()),
                  "arrays": n_arrays, "largest": rows[:_TOP_ARRAYS]}
        tr = self._tracer
        if tr is not None:
            tr.emit("memory", what="census", arrays=n_arrays,
                    total_bytes=total,
                    **{f"{p}_bytes": v for p, v in pools.items()})
        return census

    # ----------------------------------------------------------- queries --
    def memory_snapshot(self) -> Dict[str, Any]:
        """One JSON-able snapshot: per-pool live and peak bytes in both
        spaces, KV tier bytes, per-device totals from the last census, and
        the tail of the watermark ring.  The ``ops_server`` detection
        method (``/memory``) and the schema the tests pin.  Invariant:
        ``sum(pool device_bytes) == totals.device_bytes`` (``other`` is
        the census residual, so conservation holds by construction)."""
        with self._lock:
            by = dict(self._bytes)
            peak = dict(self._peak)
            pools = {p: {"device_bytes": by[("device", p)],
                         "host_bytes": by[("host", p)],
                         "device_peak_bytes": peak[("device", p)],
                         "host_peak_bytes": peak[("host", p)]}
                     for p in POOLS}
            totals = {
                "device_bytes": sum(by[("device", p)] for p in POOLS),
                "host_bytes": sum(by[("host", p)] for p in POOLS),
                "device_peak_bytes": self._peak_total["device"],
                "host_peak_bytes": self._peak_total["host"],
            }
            return {
                "pools": pools,
                "kv_tiers": {t: {"bytes": self._kv_tiers[t],
                                 "peak_bytes": self._kv_tier_peak[t]}
                             for t in KV_TIERS},
                "totals": totals,
                "per_device": dict(self._per_device),
                "census": dict(self._census_meta)
                if self._census_meta else None,
                "counts": {"watermarks": self._n_watermarks,
                           "census_runs": self._n_census,
                           "registered_trees": len(self._trees)},
                "watermarks": list(self._ring)[-16:],
            }

    def forensics(self, window: int = 64) -> Dict[str, Any]:
        """The OOM post-mortem payload the flight recorder writes as a
        dump section: pools ranked by live bytes, recent growth per pool
        over the last ``window`` retained samples, the largest live
        arrays (with tree paths) from the last census, the watermark
        ring, and the allocator's own stats where the backend exposes
        them.  Never raises — a crash handler that crashes destroys the
        evidence."""
        try:
            with self._lock:
                by = dict(self._bytes)
                series = list(self._series)[-window:]
                largest = list(self._largest)
                ring = list(self._ring)
            top = sorted(
                ({"space": s, "pool": p, "bytes": v}
                 for (s, p), v in by.items() if v > 0),
                key=lambda r: -r["bytes"])
            first_seen: Dict[Tuple[str, str], int] = {}
            last_seen: Dict[Tuple[str, str], int] = {}
            for ts, space, pool, total in series:
                key = (space, pool)
                first_seen.setdefault(key, total)
                last_seen[key] = total
            growth = [{"space": s, "pool": p,
                       "delta_bytes": last_seen[(s, p)] - first_seen[(s, p)]}
                      for (s, p) in last_seen
                      if last_seen[(s, p)] != first_seen[(s, p)]]
            growth.sort(key=lambda r: -r["delta_bytes"])
            try:
                alloc = device_allocator_stats()
            except Exception as e:  # pragma: no cover - backend-specific
                alloc = {"error": repr(e)}
            return {"top_pools": top, "recent_growth": growth,
                    "largest_arrays": largest, "watermarks": ring,
                    "allocator": alloc}
        except Exception as e:  # pragma: no cover - crash-path guard
            self._log.warning("memory ledger: forensics failed: %s", e)
            return {"error": repr(e)}

    # ----------------------------------------------------------- exports --
    def prometheus_text(self, namespace: str = "paddle_tpu_memory") -> str:
        """Text exposition of the snapshot: per-pool live/peak byte gauges
        in both spaces, per-tier KV bytes, space totals, and event
        counters — what ``ops_server`` merges into ``GET /metrics``."""
        from .utils.stats import StatRegistry, prometheus_text as _pt
        snap = self.memory_snapshot()
        gauges: Dict[str, float] = {}
        for p, row in snap["pools"].items():
            for field, v in row.items():
                gauges[f"{p}_{field}"] = v
        for t, row in snap["kv_tiers"].items():
            gauges[f"kv_{t}_bytes"] = row["bytes"]
            gauges[f"kv_{t}_peak_bytes"] = row["peak_bytes"]
        for field, v in snap["totals"].items():
            gauges[f"total_{field}"] = v
        if snap["census"]:
            gauges["live_arrays"] = snap["census"]["arrays"]
        counters = {"watermark_events_total": snap["counts"]["watermarks"],
                    "census_runs_total": snap["counts"]["census_runs"]}
        return _pt(StatRegistry(), namespace=namespace,
                   extra_gauges=gauges, extra_counters=counters)

    def to_dict(self) -> Dict[str, Any]:
        """Snapshot + retained sample series + forensics — the
        ``dump_json`` payload and the flight-recorder artifact."""
        with self._lock:
            series = [[ts, s, p, b] for ts, s, p, b in self._series]
        return {"kind": "memory", "snapshot": self.memory_snapshot(),
                "series": series, "forensics": self.forensics()}

    def dump_json(self, path: str):
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)

    def to_chrome_counters(self, pid: str = "paddle_tpu.memory"
                           ) -> List[Dict[str, Any]]:
        """Chrome-trace counter ("C") events: per-pool live bytes after
        each retained sample, one stacked track per space — merges next
        to tracer span rows (``tools/trace_to_chrome.py --memory``)."""
        return chrome_counters_from_memory_dump(self.to_dict(), pid=pid)

    # ---------------------------------------------------------- lifecycle --
    def activate(self) -> "MemoryLedger":
        """Install as the process-wide active memory ledger (the seam the
        kv_store / aot-cache / trainer instrumentation reports through).
        Also a context manager."""
        self._prev_active = set_active_memory_ledger(self)
        return self

    def deactivate(self):
        set_active_memory_ledger(self._prev_active)
        self._prev_active = None

    __enter__ = activate

    def __exit__(self, *exc):
        self.deactivate()
        return False


def chrome_counters_from_memory_dump(data: Dict[str, Any],
                                     pid: str = "paddle_tpu.memory"
                                     ) -> List[Dict[str, Any]]:
    """``MemoryLedger.to_dict()`` / ``dump_json`` payload → chrome counter
    events (offline twin of ``to_chrome_counters``, used by
    ``tools/trace_to_chrome.py --memory``).  One counter track per space
    so device HBM and host bytes stack separately on the timeline."""
    out: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": pid}}]
    cur: Dict[str, Dict[str, int]] = {s: {} for s in SPACES}
    for ts, space, pool, total in data.get("series", []):
        if space not in cur:
            continue
        cur[space][pool] = total
        out.append({"name": f"{space}_memory_bytes", "ph": "C", "pid": pid,
                    "ts": float(ts) * 1e6,
                    "args": dict(cur[space])})
    return out


# --------------------------------------------------------------------------
# process-wide active memory ledger
# --------------------------------------------------------------------------

_active_memory: Optional[MemoryLedger] = None


def set_active_memory_ledger(ledger: Optional[MemoryLedger]
                             ) -> Optional[MemoryLedger]:
    """Install the process-wide active memory ledger (or None) and return
    the previous one — the ``set_active_ledger`` convention.  Seams that
    cannot be threaded a handle (kv tier transitions, aot blob writes,
    the per-step state re-registration) report through this."""
    global _active_memory
    prev = _active_memory
    _active_memory = ledger
    return prev


def current_memory_ledger() -> Optional[MemoryLedger]:
    return _active_memory


def account_bytes(pool: str, delta: int, space: str = "host",
                  tier: Optional[str] = None):
    """``account`` on the active ledger; a no-op when none is active (the
    one-check-zero-cost contract every seam shares)."""
    led = _active_memory
    if led is None:
        return
    led.account(pool, delta, space=space, tier=tier)
