"""Fleet observability plane: cross-process telemetry federation, a
durable metric spool, and fleet-level rollups.

Every observability layer so far — tracer rings (PR 8), the goodput
ledger (PR 7), SLO burn (PR 10), the memory ledger (PR 17) — lives in
ONE process and dies with it.  :class:`FleetCollector` is the pull-based
federation plane over the per-process ops endpoints
(:class:`~paddle_tpu.ops_server.OpsServer`): it scrapes N targets'
``/metrics`` + JSON surfaces on an interval, parses the Prometheus text
itself (ONE parser, round-trip-tested against every emitter family so
emitters and parser cannot drift), spools every sample to disk so metric
history finally survives process death, and computes **fleet rollups**
no single process can see:

- **global goodput** — fleet compute-seconds over fleet elapsed-seconds,
  the same merge discipline as ``RunLedger.aggregate`` (PR 7), computed
  from the scraped ``/ledger`` snapshots;
- **fleet MFU** — per-target MFU gauges weighted by each target's costed
  wall (``model_flops_wall_seconds``), so an idle replica cannot dilute
  the fleet number;
- **merged TTFT/ITL percentiles** — each target's ``/slo`` response
  carries its time-bucketed :class:`~paddle_tpu.telemetry_slo
  .PercentileSketch` es serialized (``sketch_buckets``); the collector
  reconstructs and **merges** them (the DDSketch merge that motivated
  the log-bucketed design), so ``fleet ttft_p99`` is a real quantile of
  the union of samples, not an average of per-replica quantiles;
- **straggler skew** — max per-target compute-seconds over the mean
  (1.0 = perfectly balanced), mirroring the cross-replica accounting of
  ``fleet.metrics.all_reduce_metrics`` at the ops layer;
- **fleet SLO burn** — an internal :class:`~paddle_tpu.telemetry_slo
  .SLOMonitor` on the collector's clock re-runs the multi-window
  burn-rate machinery over the MERGED series: closed sketch buckets
  feed ``ttft_s``/``itl_s`` exactly once (per-target bucket cursors
  dedup re-scrapes), and every scrape observes the scalar rollups
  (``goodput_global``, ``tokens_per_s``, …) — a ``floor`` objective on
  ``tokens_per_s`` IS the fleet throughput-regression detector.

**Scrape semantics.**  Each target is scraped with a per-target timeout;
a failing target backs off exponentially (bounded by
``backoff_max_s``) and is marked — never silently merged:

- ``ok``      — scraped successfully within ``stale_after_s``;
- ``stale``   — previously healthy, but the last good scrape is older
  than ``stale_after_s``: its data is EXCLUDED from every rollup and
  the gap is labeled in the snapshot (status, age, consecutive
  failures, last error);
- ``down``    — never scraped successfully.

**The spool.**  :class:`TelemetrySpool` is an append-only JSONL segment
store (``spool-<n>.jsonl``): size-based rotation at ``segment_bytes``,
retention capped at ``max_segments`` (oldest deleted), every record
stamped with a monotonic ``seq``.  Restart resumes the open segment:
a torn tail line (crash mid-write) is truncated, ``seq`` continues from
the last durable record — no duplicates, no silently lost durable
samples.  It is the time-series complement of the FlightRecorder's
point-in-time dumps; the collector itself is a FlightRecorder source
(``to_dict`` → last fleet snapshot + spool tail as ``fleet.json``).

**Surfaces.**  ``GET /fleet`` on an :class:`OpsServer` the collector is
attached to; ``paddle_tpu_fleet_*`` federation gauges on the
collector's own ``prometheus_text`` (per-target ``up``/age/goodput/
TTFT labeled gauges + the rollups); ``tools/fleet_top.py`` renders the
same ``fleet_snapshot()`` as a live terminal dashboard; and
:func:`replay_regressions` re-runs the burn-rate machinery over spooled
rollup records post-hoc — the offline regression detector.

Targets come in three transports, all sharing one scrape path:

- ``url=``     a live ops endpoint scraped over HTTP (stdlib urllib,
  per-request timeout);
- ``server=``  an in-process :class:`OpsServer` (rendered directly, no
  socket — what ``bench.py`` and the sim fleet use);
- ``fetch=``   a callable ``fetch(path) -> str | dict | None`` (the
  fake-clock test harness; ``None`` = endpoint absent).

Zero cost when absent: nothing in the serving/train hot paths knows the
collector exists — it is a pure pull reader over surfaces that were
already being exported, so engine/train lowerings are byte-identical
with or without one (the PR 2 off-path discipline, pinned by test).

The clock is injectable (``clock=``): scrape cadence, staleness and
burn-rate lifecycles are all testable on a fake clock with no sleeps.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import urllib.error
import urllib.request
from typing import (Any, Callable, Dict, Iterable, List, NamedTuple,
                    Optional, Tuple)

from .telemetry_slo import Objective, PercentileSketch, SLOMonitor
from .utils.stats import StatRegistry, prom_sample, prometheus_text

__all__ = ["FleetCollector", "TelemetrySpool", "ParsedSample",
           "parse_prometheus_text", "replay_regressions"]


# --------------------------------------------------------------------------
# Prometheus text parser (the emitter's inverse — utils/stats.py)
# --------------------------------------------------------------------------

class ParsedSample(NamedTuple):
    """One exposition sample: metric name, label dict (string values,
    insertion order preserved — the emitter's order), float value."""
    name: str
    labels: Dict[str, str]
    value: float


#: ``name{labels} value`` / ``name value`` — names as the emitter's
#: ``_prom_name`` sanitizer produces them.
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$')
#: one label pair; the value body is any run of non-quote/non-backslash
#: chars or escape pairs — the exact language ``prom_escape_label`` emits.
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(value: str) -> str:
    """Inverse of ``utils.stats.prom_escape_label``: ``\\\\`` → ``\\``,
    ``\\"`` → ``"``, ``\\n`` → newline, left to right."""
    out: List[str] = []
    i, n = 0, len(value)
    while i < n:
        c = value[i]
        if c == "\\" and i + 1 < n:
            nxt = value[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == '"':
                out.append('"')
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def parse_prometheus_text(text: str) -> Dict[str, Any]:
    """Parse one text exposition (format 0.0.4, the dialect every
    ``prometheus_text`` emitter in this tree produces through
    ``utils.stats.prom_sample``) into::

        {"samples": [ParsedSample, ...],      # exposition order
         "types":   {metric_name: kind},      # from # TYPE lines
         "errors":  [unparseable line, ...]}  # never raises mid-scrape

    Unparseable lines are collected, not raised — one corrupt line from
    a half-written response must not void the rest of the scrape."""
    samples: List[ParsedSample] = []
    types: Dict[str, str] = {}
    errors: List[str] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(line)
            continue
        name, label_body, raw = m.group(1), m.group(2), m.group(3)
        labels: Dict[str, str] = {}
        if label_body:
            consumed = 0
            for lm in _LABEL_RE.finditer(label_body):
                labels[lm.group(1)] = _unescape_label(lm.group(2))
                consumed += 1
            if consumed == 0 and label_body.strip():
                errors.append(line)
                continue
        try:
            value = float(raw)
        except ValueError:
            errors.append(line)
            continue
        samples.append(ParsedSample(name, labels, value))
    return {"samples": samples, "types": types, "errors": errors}


def render_sample(sample: ParsedSample) -> str:
    """Re-render one parsed sample through the shared emitter helper —
    the round-trip the drift-guard test pins: for every line an emitter
    produced, ``render_sample(parse(line)) == line``."""
    return prom_sample(sample.name, sample.value, sample.labels or None)


# --------------------------------------------------------------------------
# durable spool
# --------------------------------------------------------------------------

_SEGMENT_RE = re.compile(r"^spool-(\d{8})\.jsonl$")


class TelemetrySpool:
    """Append-only JSONL segment spool (module docstring): size-based
    rotation, retention caps, crash-safe resume.  Records are dicts; the
    spool stamps each with a monotonic ``seq`` that survives restart —
    the no-duplicate/no-loss contract the fleet test pins."""

    def __init__(self, directory: str, *, segment_bytes: int = 262144,
                 max_segments: int = 8,
                 logger: Optional[logging.Logger] = None):
        if int(segment_bytes) < 1024:
            raise ValueError("segment_bytes must be >= 1024")
        if int(max_segments) < 2:
            raise ValueError("max_segments must be >= 2 (rotation needs "
                             "a current segment plus at least one kept)")
        self.directory = str(directory)
        self.segment_bytes = int(segment_bytes)
        self.max_segments = int(max_segments)
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        os.makedirs(self.directory, exist_ok=True)
        # append/rotate/retention and the seq counter are driven from the
        # scrape thread while /fleet handlers call tail()/segments()
        self._lock = threading.Lock()
        self._seq = 0                 # guarded-by: _lock
        self._seg_index = 1           # guarded-by: _lock
        self._seg_bytes = 0           # guarded-by: _lock
        self._fh = None               # guarded-by: _lock
        self._resume()

    # ------------------------------------------------------------ resume --

    def _segment_paths(self) -> List[Tuple[int, str]]:
        out = []
        for fn in os.listdir(self.directory):
            m = _SEGMENT_RE.match(fn)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, fn)))
        out.sort()
        return out

    def _resume(self):
        """Crash-safe resume: repair a torn tail line on the newest
        segment (truncate — the record was never durable), recover the
        last durable ``seq``, and continue appending to that segment
        when it is still under the size cap."""
        segments = self._segment_paths()
        if not segments:
            return
        idx, path = segments[-1]
        with open(path, "rb") as f:
            data = f.read()
        good = data
        if data:
            if not data.endswith(b"\n"):
                cut = data.rfind(b"\n")
                good = data[:cut + 1] if cut >= 0 else b""
            # a torn write that DID land its newline still shows up as
            # unparseable JSON on the final line — drop it the same way
            while good:
                last = good[:-1].rfind(b"\n")
                tail = good[last + 1:]
                try:
                    json.loads(tail)
                    break
                except ValueError:
                    good = good[:last + 1] if last >= 0 else b""
        if good != data:
            self._log.warning(
                "telemetry spool: truncating torn tail of %s "
                "(%d -> %d bytes)", path, len(data), len(good))
            with open(path, "wb") as f:
                f.write(good)
        # the last durable seq across every surviving segment
        for _idx, p in reversed(segments):
            last_rec = None
            try:
                with open(p, "r") as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            last_rec = line
            except OSError:
                continue
            if last_rec is not None:
                try:
                    self._seq = int(json.loads(last_rec).get("seq", 0))
                    break
                except (ValueError, TypeError):
                    continue
        size = os.path.getsize(path)
        if size < self.segment_bytes:
            self._seg_index = idx
            self._seg_bytes = size
        else:
            self._seg_index = idx + 1
            self._seg_bytes = 0

    # ------------------------------------------------------------ append --

    def _segment_path(self, idx: int) -> str:
        return os.path.join(self.directory, f"spool-{idx:08d}.jsonl")

    def _open_locked(self):
        if self._fh is None:
            self._fh = open(self._segment_path(self._seg_index), "a")
            self._seg_bytes = self._fh.tell()

    def _rotate_locked(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._seg_index += 1
        self._seg_bytes = 0
        # retention: drop oldest beyond the cap (the current, about-to-
        # open segment counts toward it)
        segments = self._segment_paths()
        excess = len(segments) + 1 - self.max_segments
        for _idx, path in segments[:max(excess, 0)]:
            try:
                os.remove(path)
            except OSError as e:
                self._log.warning("telemetry spool: retention unlink "
                                  "failed for %s: %r", path, e)

    def append(self, record: Dict[str, Any]) -> int:
        """Write one record (stamped ``seq``), flushed to the OS before
        returning — a record handed back as appended is durable against
        process death (fsync is deliberately NOT paid per record; the
        spool is telemetry, not a WAL)."""
        with self._lock:
            if self._seg_bytes >= self.segment_bytes:
                self._rotate_locked()
            self._open_locked()
            self._seq += 1
            rec = dict(record)
            rec["seq"] = self._seq
            line = json.dumps(rec) + "\n"
            self._fh.write(line)
            self._fh.flush()
            self._seg_bytes += len(line)
            return self._seq

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # ------------------------------------------------------------- reads --

    def records(self) -> List[Dict[str, Any]]:
        """Every durable record, oldest first (bounded by retention)."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
        out: List[Dict[str, Any]] = []
        for _idx, path in self._segment_paths():
            try:
                with open(path, "r") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            out.append(json.loads(line))
                        except ValueError:
                            pass          # torn tail of a live segment
            except OSError:
                continue
        return out

    def tail(self, n: int = 64) -> List[Dict[str, Any]]:
        return self.records()[-max(int(n), 1):]

    def stats(self) -> Dict[str, Any]:
        segments = self._segment_paths()
        with self._lock:
            seq = self._seq
        return {"directory": self.directory,
                "segments": len(segments),
                "bytes": sum(os.path.getsize(p) for _i, p in segments),
                "segment_bytes": self.segment_bytes,
                "max_segments": self.max_segments,
                "seq": seq}


# --------------------------------------------------------------------------
# collector
# --------------------------------------------------------------------------

#: the per-process ops surfaces one scrape covers; /metrics is the one
#: REQUIRED endpoint (its failure fails the scrape), the JSON surfaces
#: are optional per target (a train host has no /gateway — absence is
#: normal, not an error).
SCRAPE_ENDPOINTS = ("/metrics", "/ledger", "/slo", "/gateway",
                    "/kvstore", "/memory", "/autoscaler")


class _Target:
    """One scrape target's state.  Mutated only under the collector's
    lock (scrape thread vs /fleet + /metrics handler threads)."""

    __slots__ = ("name", "url", "server", "fetch", "last_ok_at",
                 "last_attempt_at", "failures", "backoff_until", "error",
                 "metrics", "endpoints", "prev_tokens", "tokens_per_s",
                 "bucket_cursors", "scrapes")

    def __init__(self, name: str, url: Optional[str],
                 server: Any, fetch: Optional[Callable[[str], Any]]):
        self.name = name
        self.url = url
        self.server = server
        self.fetch = fetch
        self.last_ok_at: Optional[float] = None
        self.last_attempt_at: Optional[float] = None
        self.failures = 0
        self.backoff_until: Optional[float] = None
        self.error: Optional[str] = None
        self.metrics: Dict[str, Any] = {"samples": [], "types": {}}
        self.endpoints: Dict[str, Any] = {}
        self.prev_tokens: Optional[Tuple[float, float]] = None
        self.tokens_per_s: Optional[float] = None
        # per-metric sketch-bucket cursor: newest bucket key already
        # merged into the fleet SLO feed — the exactly-once dedup that
        # keeps overlapping scrapes from double-counting samples
        self.bucket_cursors: Dict[str, float] = {}
        self.scrapes = 0


class FleetCollector:
    """Cross-process telemetry federation (module docstring).

    ``interval_s`` paces the background loop (``start()``); with an
    injectable ``clock`` tests drive ``scrape_once(now)`` directly.
    ``stale_after_s`` (default ``3 * interval_s``) is the labeled-gap
    window; ``timeout_s`` bounds each HTTP request; failures back off
    exponentially from ``interval_s`` up to ``backoff_max_s``.
    ``objectives`` seed the internal fleet :class:`SLOMonitor` (burn on
    the merged series — the live regression detector); ``spool_dir``
    enables the durable spool."""

    def __init__(self, *, interval_s: float = 5.0, timeout_s: float = 2.0,
                 stale_after_s: Optional[float] = None,
                 backoff_max_s: float = 60.0,
                 spool_dir: Optional[str] = None,
                 spool_segment_bytes: int = 262144,
                 spool_max_segments: int = 8,
                 objectives: Iterable[Objective] = (),
                 slo_resolution_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None,
                 logger: Optional[logging.Logger] = None):
        if float(interval_s) <= 0:
            raise ValueError("interval_s must be > 0")
        if float(timeout_s) <= 0:
            raise ValueError("timeout_s must be > 0")
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.stale_after_s = (3.0 * self.interval_s
                              if stale_after_s is None
                              else float(stale_after_s))
        if self.stale_after_s <= 0:
            raise ValueError("stale_after_s must be > 0")
        self.backoff_max_s = float(backoff_max_s)
        self._clock = clock if clock is not None else time.monotonic
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        # targets / snapshot / merged sketches are written by the scrape
        # thread and read by ops-server handler threads (/fleet, the
        # federation gauges) and FlightRecorder dumps
        self._lock = threading.Lock()
        self._targets: Dict[str, _Target] = {}    # guarded-by: _lock
        self._snapshot: Optional[Dict[str, Any]] = None  # guarded-by: _lock
        self._scrapes = 0                         # guarded-by: _lock
        self.registry = StatRegistry()  # guarded-by: none (locks internally)
        # guarded-by: none (set once here; TelemetrySpool serializes its
        # own appends/reads under its private _lock)
        self.spool = (None if spool_dir is None else TelemetrySpool(
            spool_dir, segment_bytes=spool_segment_bytes,
            max_segments=spool_max_segments, logger=self._log))
        # the fleet burn/regression monitor rides the collector clock;
        # its resolution defaults to the scrape interval so one scrape
        # lands in one bucket
        self.slo = SLOMonitor(
            objectives, clock=self._clock,
            resolution_s=(self.interval_s if slo_resolution_s is None
                          else float(slo_resolution_s)),
            logger=self._log)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()  # guarded-by: none (Event is thread-safe)

    # ----------------------------------------------------------- targets --

    def add_target(self, name: str, url: Optional[str] = None, *,
                   server: Any = None,
                   fetch: Optional[Callable[[str], Any]] = None
                   ) -> "FleetCollector":
        """Register one scrape target under a unique ``name`` — exactly
        one transport: ``url`` (HTTP ops endpoint), ``server`` (an
        in-process :class:`OpsServer`, rendered without a socket), or
        ``fetch`` (a ``fetch(path)`` callable)."""
        given = [t for t in (url, server, fetch) if t is not None]
        if len(given) != 1:
            raise ValueError("add_target wants exactly one of url=, "
                             "server=, fetch=")
        if server is not None and not hasattr(server, "render"):
            raise TypeError(f"server= target must be an OpsServer-like "
                            f"object with .render(), got "
                            f"{type(server).__name__}")
        with self._lock:
            if name in self._targets:
                raise ValueError(f"target {name!r} already registered")
            self._targets[name] = _Target(
                str(name), None if url is None else url.rstrip("/"),
                server, fetch)
        return self

    def remove_target(self, name: str) -> bool:
        with self._lock:
            return self._targets.pop(name, None) is not None

    def targets(self) -> List[str]:
        with self._lock:
            return sorted(self._targets)

    # ------------------------------------------------------------ scrape --

    def _fetch_http(self, base: str, path: str) -> Any:
        req = urllib.request.Request(base + path,
                                     headers={"Accept": "*/*"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                body = resp.read().decode("utf-8", "replace")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None       # endpoint absent on this target: normal
            raise
        if path == "/metrics":
            return body
        return json.loads(body)

    def _fetch_one(self, tgt: _Target, path: str) -> Any:
        if tgt.url is not None:
            return self._fetch_http(tgt.url, path)
        if tgt.server is not None:
            return tgt.server.render(path)
        return tgt.fetch(path)

    def _scrape_target(self, tgt: _Target, now: float) -> bool:
        """Scrape every endpoint of one target; True on success.  Only
        ``/metrics`` is load-bearing — a JSON surface that errors is
        logged and skipped (absence of /gateway on a train host must
        not mark the host dead)."""
        try:
            text = self._fetch_one(tgt, "/metrics")
            if text is None:
                raise ValueError("target has no /metrics")
            parsed = parse_prometheus_text(text)
        except Exception as e:  # noqa: BLE001 — the verdict is recorded,
            # never raised: a dead target is a labeled gap
            self._on_failure(tgt, now, e)
            return False
        endpoints: Dict[str, Any] = {}
        for path in SCRAPE_ENDPOINTS[1:]:
            try:
                payload = self._fetch_one(tgt, path)
            except Exception as e:  # noqa: BLE001
                self._log.debug("fleet: %s%s failed: %r",
                                tgt.name, path, e)
                payload = None
            if payload is not None:
                endpoints[path.lstrip("/")] = payload
        with self._lock:
            tgt.metrics = parsed
            tgt.endpoints = endpoints
            tgt.last_ok_at = now
            tgt.failures = 0
            tgt.backoff_until = None
            tgt.error = None
            tgt.scrapes += 1
            self._update_tokens_locked(tgt, now)
        self.registry.add("scrapes_ok")
        return True

    def _on_failure(self, tgt: _Target, now: float, err: Exception):
        with self._lock:
            tgt.failures += 1
            tgt.error = repr(err)
            backoff = min(self.interval_s * (2.0 ** (tgt.failures - 1)),
                          self.backoff_max_s)
            tgt.backoff_until = now + backoff
        self.registry.add("scrape_errors")
        self._log.debug("fleet: scrape of %s failed (%d consecutive, "
                        "backoff %.1fs): %r", tgt.name, tgt.failures,
                        backoff, err)

    @staticmethod
    def _counter_sum(parsed: Dict[str, Any], suffix: str) -> float:
        return sum(s.value for s in parsed["samples"]
                   if s.name.endswith(suffix) and not s.labels)

    def _update_tokens_locked(self, tgt: _Target, now: float):
        """Per-target token throughput: delta of the token counters
        (serving ``tokens_emitted`` + train ``train_tokens``) between
        this scrape and the previous one, over the wall between them."""
        total = (self._counter_sum(tgt.metrics, "_tokens_emitted")
                 + self._counter_sum(tgt.metrics, "_train_tokens"))
        prev = tgt.prev_tokens
        tgt.prev_tokens = (now, total)
        if prev is None:
            tgt.tokens_per_s = None
            return
        prev_at, prev_total = prev
        dt = now - prev_at
        if dt <= 0:
            return
        # counter reset (restarted target) shows as a negative delta:
        # restart the rate from this scrape rather than report nonsense
        delta = total - prev_total
        tgt.tokens_per_s = (None if delta < 0 else delta / dt)

    def _status(self, tgt: _Target, now: float) -> str:
        if tgt.last_ok_at is None:
            return "down"
        if now - tgt.last_ok_at > self.stale_after_s:
            return "stale"
        return "ok"

    def scrape_once(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One scrape round over every due target, then rollups: the
        fleet snapshot (also retained for ``fleet_snapshot()`` /
        ``GET /fleet``), spooled when a spool is configured."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            targets = list(self._targets.values())
        self.registry.add("scrape_rounds")
        for tgt in targets:
            with self._lock:
                in_backoff = (tgt.backoff_until is not None
                              and now < tgt.backoff_until)
                tgt.last_attempt_at = now
            if in_backoff:
                continue
            self._scrape_target(tgt, now)
        snapshot = self._build_snapshot(now)
        with self._lock:
            self._scrapes += 1
            snapshot["scrapes"] = self._scrapes
            self._snapshot = snapshot
        if self.spool is not None:
            for row in snapshot["targets"]:
                self.spool.append({"kind": "target", "ts": now, **row})
            self.spool.append({"kind": "rollup", "ts": now,
                               **snapshot["rollup"]})
            snapshot["spool"] = self.spool.stats()
        return snapshot

    # ----------------------------------------------------------- rollups --

    @staticmethod
    def _target_sketches(tgt: _Target) -> Dict[str, PercentileSketch]:
        """Reconstruct one target's per-metric sketches by merging every
        serialized sketch bucket from its last /slo response."""
        slo = tgt.endpoints.get("slo") or {}
        buckets = (slo.get("sketch_buckets") or {}).get("metrics") or {}
        out: Dict[str, PercentileSketch] = {}
        for metric, per_key in buckets.items():
            merged = None
            for _key, blob in per_key.items():
                sk = PercentileSketch.from_dict(blob)
                merged = sk if merged is None else merged.merge(sk)
            if merged is not None and merged.n:
                out[metric] = merged
        return out

    def _feed_slo_locked(self, tgt: _Target, now: float):
        """Exactly-once feed of CLOSED sketch buckets into the fleet SLO
        monitor: buckets newer than the target's cursor and older than
        one resolution (still-filling buckets wait for the next scrape)
        merge into the fleet series; the cursor advances."""
        slo = tgt.endpoints.get("slo") or {}
        export = slo.get("sketch_buckets") or {}
        res = float(export.get("resolution_s") or 0.0)
        for metric, per_key in (export.get("metrics") or {}).items():
            cursor = tgt.bucket_cursors.get(metric)
            newest_merged = cursor
            for key_s, blob in per_key.items():
                key = float(key_s)
                if cursor is not None and key <= cursor:
                    continue
                if res > 0 and key + res > float(slo.get("now", now)):
                    continue                    # still filling
                self.slo.observe_sketch(
                    metric, PercentileSketch.from_dict(blob), now=now)
                if newest_merged is None or key > newest_merged:
                    newest_merged = key
            if newest_merged is not None:
                tgt.bucket_cursors[metric] = newest_merged

    def _build_snapshot(self, now: float) -> Dict[str, Any]:
        with self._lock:
            targets = list(self._targets.values())
            rows: List[Dict[str, Any]] = []
            ok_rows: List[Tuple[_Target, Dict[str, Any]]] = []
            for tgt in targets:
                status = self._status(tgt, now)
                ledger = tgt.endpoints.get("ledger") or {}
                gw = tgt.endpoints.get("gateway") or {}
                resil = gw.get("resilience") or {}
                occ = gw.get("occupancy") or {}
                sketches = self._target_sketches(tgt)
                ttft = sketches.get("ttft_s")
                mfu_samples = [s.value for s in tgt.metrics["samples"]
                               if s.name.endswith("_mfu")
                               and not s.labels]
                row = {
                    "target": tgt.name,
                    "status": status,
                    "url": tgt.url,
                    "age_s": (None if tgt.last_ok_at is None
                              else round(now - tgt.last_ok_at, 3)),
                    "scrapes": tgt.scrapes,
                    "consecutive_failures": tgt.failures,
                    "error": tgt.error,
                    "goodput": ledger.get("goodput"),
                    "compute_s": (ledger.get("buckets_s")
                                  or {}).get("compute"),
                    "elapsed_s": ledger.get("elapsed_s"),
                    "mfu": (max(mfu_samples) if mfu_samples else None),
                    "ttft_p99": (ttft.quantile(0.99) if ttft else None),
                    "ttft_p50": (ttft.quantile(0.50) if ttft else None),
                    "tokens_per_s": tgt.tokens_per_s,
                    "occupancy": occ.get("value"),
                    "queued": occ.get("queued"),
                    "breakers_open": resil.get("breakers_open"),
                    "brownout_level": resil.get("brownout_level"),
                }
                rows.append(row)
                if status == "ok":
                    ok_rows.append((tgt, row))
                    self._feed_slo_locked(tgt, now)
            # ---- merged percentiles over the healthy targets only: a
            # stale target's last sketches must not haunt the rollup
            merged: Dict[str, PercentileSketch] = {}
            for tgt, _row in ok_rows:
                for metric, sk in self._target_sketches(tgt).items():
                    if metric in merged:
                        merged[metric].merge(sk)
                    else:
                        fresh = PercentileSketch(alpha=sk.alpha)
                        merged[metric] = fresh.merge(sk)
        computes = [r["compute_s"] for _t, r in ok_rows
                    if r["compute_s"] is not None]
        elapsed = [r["elapsed_s"] for _t, r in ok_rows
                   if r["elapsed_s"] is not None
                   and r["compute_s"] is not None]
        goodput_global = (sum(computes) / max(sum(elapsed), 1e-9)
                          if computes and elapsed else None)
        skew = None
        if len(computes) >= 2 and sum(computes) > 0:
            skew = max(computes) / (sum(computes) / len(computes))
        # fleet MFU: per-target MFU weighted by its costed wall so idle
        # targets cannot dilute the number; unweighted mean as fallback
        mfu_rows = []
        for tgt, row in ok_rows:
            if row["mfu"] is None:
                continue
            wall = self._counter_sum(tgt.metrics,
                                     "_model_flops_wall_seconds")
            mfu_rows.append((row["mfu"], wall))
        fleet_mfu = None
        if mfu_rows:
            wsum = sum(w for _m, w in mfu_rows)
            if wsum > 0:
                fleet_mfu = sum(m * w for m, w in mfu_rows) / wsum
            else:
                fleet_mfu = sum(m for m, _w in mfu_rows) / len(mfu_rows)
        rates = [r["tokens_per_s"] for _t, r in ok_rows
                 if r["tokens_per_s"] is not None]
        ttft_m = merged.get("ttft_s")
        itl_m = merged.get("itl_s")
        rollup = {
            "targets": len(rows),
            "targets_ok": sum(1 for r in rows if r["status"] == "ok"),
            "targets_stale": sum(1 for r in rows
                                 if r["status"] == "stale"),
            "targets_down": sum(1 for r in rows
                                if r["status"] == "down"),
            "goodput_global": goodput_global,
            "fleet_mfu": fleet_mfu,
            "fleet_ttft_p99": (ttft_m.quantile(0.99) if ttft_m else None),
            "fleet_ttft_p50": (ttft_m.quantile(0.50) if ttft_m else None),
            "fleet_itl_p99": (itl_m.quantile(0.99) if itl_m else None),
            "straggler_skew": skew,
            "tokens_per_s": (sum(rates) if rates else None),
        }
        # the scalar rollup series feed the fleet burn monitor — a
        # floor objective on any of these is a live regression detector
        for metric, value in (("goodput_global", goodput_global),
                              ("tokens_per_s", rollup["tokens_per_s"]),
                              ("fleet_mfu", fleet_mfu),
                              ("straggler_skew", skew)):
            if value is not None:
                self.slo.observe(metric, float(value), now=now)
        slo_rows = self.slo.evaluate(now)
        return {
            "now": now,
            "interval_s": self.interval_s,
            "stale_after_s": self.stale_after_s,
            "targets": rows,
            "rollup": rollup,
            "slo": {"status": slo_rows,
                    "alerts_firing": sum(1 for r in slo_rows
                                         if r["state"] == "firing")},
        }

    # ---------------------------------------------------------- surfaces --

    def fleet_snapshot(self) -> Dict[str, Any]:
        """The last scrape's snapshot — what ``GET /fleet`` serves and
        ``tools/fleet_top.py`` renders (one snapshot, two views).  A
        collector that never scraped reports its configuration and an
        empty target list rather than erroring."""
        with self._lock:
            if self._snapshot is not None:
                snap = dict(self._snapshot)
            else:
                snap = {"now": None, "scrapes": 0,
                        "interval_s": self.interval_s,
                        "stale_after_s": self.stale_after_s,
                        "targets": [],
                        "rollup": {"targets": len(self._targets),
                                   "targets_ok": 0, "targets_stale": 0,
                                   "targets_down": len(self._targets)},
                        "slo": None}
        if self.spool is not None:
            snap["spool"] = self.spool.stats()
        return snap

    def to_dict(self) -> Dict[str, Any]:
        """FlightRecorder source contract: the crash dump's ``fleet.json``
        — last fleet snapshot plus the spool tail, so a post-mortem
        shows what the rest of the fleet looked like."""
        out = {"snapshot": self.fleet_snapshot()}
        if self.spool is not None:
            out["spool_tail"] = self.spool.tail(64)
        return out

    def prometheus_text(self, namespace: str = "paddle_tpu_fleet") -> str:
        """The federation gauges: rollups plus per-target labeled
        ``up``/staleness/goodput/TTFT gauges — what a meta-collector one
        level up would scrape."""
        snap = self.fleet_snapshot()
        lines = [prometheus_text(self.registry, namespace=namespace)
                 .rstrip("\n")]
        rollup = snap.get("rollup") or {}
        for key in ("targets", "targets_ok", "targets_stale",
                    "targets_down", "goodput_global", "fleet_mfu",
                    "fleet_ttft_p99", "fleet_itl_p99", "straggler_skew",
                    "tokens_per_s"):
            v = rollup.get(key)
            if v is not None:
                lines.append(f"# TYPE {namespace}_{key} gauge")
                lines.append(prom_sample(f"{namespace}_{key}", v))
        per_target = (("up", lambda r: 1.0 if r["status"] == "ok"
                       else 0.0),
                      ("age_seconds", lambda r: r["age_s"]),
                      ("goodput", lambda r: r["goodput"]),
                      ("ttft_p99_seconds", lambda r: r["ttft_p99"]),
                      ("tokens_per_second", lambda r: r["tokens_per_s"]))
        for suffix, get in per_target:
            rows = [(r["target"], get(r)) for r in snap.get("targets", [])]
            rows = [(t, v) for t, v in rows if v is not None]
            if not rows:
                continue
            lines.append(f"# TYPE {namespace}_target_{suffix} gauge")
            for target, v in rows:
                lines.append(prom_sample(f"{namespace}_target_{suffix}",
                                         v, {"target": target}))
        spool = snap.get("spool")
        if spool is not None:
            for key in ("segments", "bytes", "seq"):
                lines.append(f"# TYPE {namespace}_spool_{key} gauge")
                lines.append(prom_sample(f"{namespace}_spool_{key}",
                                         spool[key]))
        return "\n".join(lines) + "\n"

    # --------------------------------------------------------- lifecycle --

    def start(self) -> "FleetCollector":
        """Scrape on a daemon thread every ``interval_s`` (real-clock
        deployments; fake-clock tests call ``scrape_once`` directly)."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.scrape_once()
                except Exception:  # noqa: BLE001 — the loop must survive
                    # any one broken scrape round
                    self._log.exception("fleet: scrape round failed")
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-collector")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)
        if self.spool is not None:
            self.spool.close()


# --------------------------------------------------------------------------
# offline regression detection over a spool
# --------------------------------------------------------------------------

def replay_regressions(records: Iterable[Dict[str, Any]],
                       objectives: Iterable[Objective], *,
                       resolution_s: float = 5.0,
                       horizon_s: float = 3600.0) -> Dict[str, Any]:
    """Re-run the multi-window burn-rate machinery over spooled
    ``rollup`` records (``TelemetrySpool.records()`` or any JSONL tail):
    every numeric rollup field becomes a sample series named after the
    field (``tokens_per_s``, ``goodput_global``, …) at its recorded
    ``ts``, the objectives are evaluated at each step, and the final
    snapshot (status rows + every transition fired during the replay) is
    returned — the offline complement of the collector's live fleet SLO
    monitor, e.g. a ``floor`` objective on ``tokens_per_s`` firing on a
    throughput drop between scrape windows."""
    rollups = [r for r in records if r.get("kind") == "rollup"
               and r.get("ts") is not None]
    rollups.sort(key=lambda r: float(r["ts"]))
    last_ts = float(rollups[-1]["ts"]) if rollups else 0.0
    mon = SLOMonitor(objectives, clock=lambda: last_ts,
                     resolution_s=resolution_s, horizon_s=horizon_s)
    for rec in rollups:
        ts = float(rec["ts"])
        for key, value in rec.items():
            if key in ("kind", "ts", "seq") or value is None:
                continue
            if isinstance(value, (int, float)) \
                    and not isinstance(value, bool):
                mon.observe(key, float(value), now=ts)
        mon.evaluate(ts)
    snap = mon.snapshot(last_ts)
    snap["replayed_records"] = len(rollups)
    return snap
