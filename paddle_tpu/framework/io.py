"""Checkpoint I/O (reference: python/paddle/framework/io.py:553 save, :769
load — pickle state_dicts with .pdparams/.pdopt convention; >4GB handled by
pickle protocol 4)."""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Parameter, Tensor


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": obj.numpy(),
                "stop_gradient": obj.stop_gradient, "name": obj.name,
                "is_param": isinstance(obj, Parameter)}
    if hasattr(obj, "shape") and hasattr(obj, "dtype") and not isinstance(obj, np.ndarray):
        return {"__tensor__": True, "data": np.asarray(obj), "stop_gradient": True,
                "name": None, "is_param": False}
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_serializable(v) for v in obj)
    return obj


def _from_serializable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            data = obj["data"]
            if return_numpy:
                return data
            cls = Parameter if obj.get("is_param") else Tensor
            if cls is Parameter:
                t = Parameter(data, name=obj.get("name"))
            else:
                t = Tensor(data, stop_gradient=obj.get("stop_gradient", True),
                           name=obj.get("name"))
            return t
        return {k: _from_serializable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_serializable(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs) -> None:
    """``paddle.save`` parity.  Reports its wall time to the active
    goodput ledger as ``checkpoint_save`` (``telemetry_ledger``; no-op
    when none is active)."""
    from ..telemetry_ledger import ledger_span
    if protocol < 2 or protocol > 5:
        raise ValueError("protocol must be in [2, 5]")
    with ledger_span("checkpoint_save"):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        payload = _to_serializable(obj)
        with open(path, "wb") as f:
            pickle.dump(payload, f, protocol=protocol)


def load(path: str, **configs) -> Any:
    """``paddle.load`` parity.  Reports its wall time to the active
    goodput ledger as ``checkpoint_restore``."""
    from ..telemetry_ledger import ledger_span
    if not os.path.exists(path):
        raise ValueError(f"path {path} does not exist")
    with ledger_span("checkpoint_restore"):
        with open(path, "rb") as f:
            payload = pickle.load(f)
        return _from_serializable(payload,
                                  return_numpy=configs.get("return_numpy",
                                                           False))
