"""SLO engine: declarative objectives + multi-window burn-rate alerting
over the telemetry streams.

PR 2/4/7/8 record everything an operator could ask about — TTFT and
inter-token samples (``telemetry.Tracer``), shed/expired/failed counts
(``gateway.ServingGateway``), goodput (``telemetry_ledger.RunLedger``) —
but nothing *judges* them: an on-call still has to stare at ``/metrics``
and decide whether the service is in trouble.  :class:`SLOMonitor` closes
that loop with the SRE-standard machinery:

**Declarative objectives** (:class:`Objective`).  Three kinds:

- ``latency`` — "p-quantile of ``metric`` stays under ``target``":
  operationally "at most ``1 - compliance`` of samples may exceed
  ``target``" (a TTFT p99 ≤ 500ms objective is ``compliance=0.99,
  target=0.5``).  The bad-fraction over a window divided by the error
  budget (``1 - compliance``) is the window's **burn rate** — burn 1.0
  spends budget exactly as fast as allowed, burn 10 spends it 10×.
- ``ratio`` — "``bad`` events stay under ``target`` fraction of
  ``total``" (shed rate, error rate); burn = (bad/total) / target.
- ``floor`` — "``metric`` samples stay ABOVE ``target``" (the goodput
  floor from the PR 7 ledger); bad = sample < target, budget =
  ``1 - compliance``.

**Multi-window burn-rate alerting.**  An objective alerts only when its
burn rate exceeds ``burn_threshold`` on **every** window (classic
long+short pairing: the long window proves sustained damage, the short
window proves it is STILL happening, so a recovered incident stops
alerting without waiting out the long window).  The alert walks
``inactive → pending`` (condition holds) ``→ firing`` (held for
``for_s``) ``→ resolved`` (burn below ``resolve_ratio × burn_threshold``
on every window for ``clear_s`` — the hysteresis band, so an SLI
hovering exactly at the threshold cannot flap the alert).  Transitions
are emitted as ``slo`` events on the attached tracer (ring buffer +
chrome export), kept in a bounded local history, and exported via
``snapshot()`` (the ops server's ``GET /slo``) and ``prometheus_text()``
(labeled ``burn_rate``/``alert_state``/``sli`` gauges rendered through
``utils.stats.prom_sample`` — the shared escaping helper).

**Storage.**  Sample metrics land in a ring of time-bucketed
:class:`PercentileSketch` es (log-bucketed, mergeable — a window query
merges its buckets' sketches; relative error ``alpha``, default 2%);
counters land in time-bucketed sums.  Both are bounded by
``horizon_s / resolution_s`` buckets per metric, so a long-lived monitor
holds constant memory regardless of traffic.

**Feeds.**  Push: ``Tracer.set_slo`` (TTFT/ITL samples, terminal
counts), ``ServingGateway.set_slo`` (gateway-level TTFT, submitted/shed/
expired/failed counts), or direct ``observe``/``count`` calls.  Pull:
``attach_ledger`` samples the goodput gauge at every ``evaluate()``.
Everything is zero-cost for producers when no monitor is attached (the
one-attribute-check contract the whole telemetry stack follows).

The clock is injectable (``clock=``), so burn-rate lifecycles are
testable with a fake clock — no sleeps anywhere.

No single reference counterpart: this is the alerting layer of
site-reliability practice (multi-window multi-burn-rate alerts) composed
over the reference's monitor.h counters.
"""

from __future__ import annotations

import collections
import logging
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .utils.stats import StatRegistry, prom_sample, prometheus_text

__all__ = ["PercentileSketch", "Objective", "SLOMonitor"]

#: alert states, in escalation order (prometheus gauge encoding)
ALERT_STATES = ("inactive", "pending", "firing")


class PercentileSketch:
    """Mergeable log-bucketed quantile sketch (the DDSketch discipline).

    Values map to buckets ``i = ceil(log_gamma(v))`` with
    ``gamma = (1 + alpha) / (1 - alpha)``, giving every quantile a
    relative error of at most ``alpha``.  ``merge`` adds bucket counts —
    merging per-time-bucket sketches answers "p99 over the last N
    seconds" without retaining samples; merging per-replica sketches
    would answer fleet quantiles the same way.  Non-positive values clamp
    to the zero bucket (latencies and rates are non-negative)."""

    __slots__ = ("alpha", "_gamma", "_lg", "counts", "zero", "n",
                 "min", "max", "sum")

    def __init__(self, alpha: float = 0.02):
        if not 0.0 < alpha < 1.0:
            raise ValueError("alpha must be in (0, 1)")
        self.alpha = float(alpha)
        self._gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._lg = math.log(self._gamma)
        # guarded-by: none on all sketch state: sketches are owned by a
        # single SLOMonitor windowed store and only touched under its
        # _lock; standalone sketches (fleet rollup merges) are per-call
        # locals that never escape one thread
        self.counts: Dict[int, int] = {}  # guarded-by: none (owner-locked, see above)
        self.zero = 0                     # guarded-by: none (owner-locked, see above)
        self.n = 0                        # guarded-by: none (owner-locked, see above)
        self.min: Optional[float] = None  # guarded-by: none (owner-locked, see above)
        self.max: Optional[float] = None  # guarded-by: none (owner-locked, see above)
        self.sum = 0.0                    # guarded-by: none (owner-locked, see above)

    def _index(self, v: float) -> int:
        return math.ceil(math.log(v) / self._lg)

    def add(self, v: float, count: int = 1):
        v = float(v)
        count = int(count)
        self.n += count
        self.sum += v * count
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if v <= 0.0:
            self.zero += count
            return
        i = self._index(v)
        self.counts[i] = self.counts.get(i, 0) + count

    def merge(self, other: "PercentileSketch") -> "PercentileSketch":
        if other.alpha != self.alpha:
            raise ValueError("cannot merge sketches with different alpha")
        for i, c in other.counts.items():
            self.counts[i] = self.counts.get(i, 0) + c
        self.zero += other.zero
        self.n += other.n
        self.sum += other.sum
        for attr, pick in (("min", min), ("max", max)):
            o = getattr(other, attr)
            if o is not None:
                s = getattr(self, attr)
                setattr(self, attr, o if s is None else pick(s, o))
        return self

    def quantile(self, q: float) -> Optional[float]:
        """Value at quantile ``q`` in [0, 1] (None when empty), within
        ``alpha`` relative error; the zero bucket reports 0.0."""
        if self.n == 0:
            return None
        q = min(max(float(q), 0.0), 1.0)
        rank = q * (self.n - 1)
        acc = self.zero
        if rank < acc:
            return 0.0
        for i in sorted(self.counts):
            acc += self.counts[i]
            if rank < acc:
                # bucket midpoint in log space: 2*g^i/(g+1) — the value
                # with minimal worst-case relative error for the bucket
                return 2.0 * (self._gamma ** i) / (self._gamma + 1.0)
        return self.max

    def count_above(self, threshold: float) -> int:
        """Number of recorded samples strictly greater than ``threshold``
        (bucket-resolution: the threshold's own bucket counts as not
        above — consistent with ``alpha`` relative error)."""
        if threshold < 0.0:
            return self.n
        if self.n == 0:
            return 0
        t_idx = self._index(threshold) if threshold > 0.0 else 0
        return sum(c for i, c in self.counts.items() if i > t_idx)

    def snapshot(self) -> Dict[str, Any]:
        return {"n": self.n, "min": self.min, "max": self.max,
                "mean": (self.sum / self.n if self.n else None),
                "p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def to_dict(self) -> Dict[str, Any]:
        """Lossless wire form (exact bucket counts, JSON-safe keys) —
        what ``/slo`` ships per time bucket so a fleet collector can
        reconstruct and MERGE sketches across processes: the merged
        quantile is then a true quantile of the union of samples, not an
        average of per-process quantiles."""
        return {"alpha": self.alpha,
                "counts": {str(i): c for i, c in self.counts.items()},
                "zero": self.zero, "n": self.n, "min": self.min,
                "max": self.max, "sum": self.sum}

    @classmethod
    def from_dict(cls, blob: Dict[str, Any]) -> "PercentileSketch":
        sk = cls(alpha=float(blob.get("alpha", 0.02)))
        sk.counts = {int(i): int(c)
                     for i, c in (blob.get("counts") or {}).items()}
        sk.zero = int(blob.get("zero", 0))
        sk.n = int(blob.get("n", 0))
        sk.min = blob.get("min")
        sk.max = blob.get("max")
        sk.sum = float(blob.get("sum", 0.0))
        return sk


class _TimeBuckets:
    """Ring of per-time-bucket payloads: ``resolution_s``-wide buckets,
    pruned past ``horizon_s`` — bounded memory for any traffic rate."""

    __slots__ = ("resolution", "horizon", "buckets")

    def __init__(self, resolution_s: float, horizon_s: float):
        self.resolution = float(resolution_s)
        self.horizon = float(horizon_s)
        # guarded-by: none (rings are owned by SLOMonitor's _samples /
        # _counters maps and only touched under its _lock)
        self.buckets: Dict[float, Any] = {}

    def _key(self, now: float) -> float:
        return math.floor(now / self.resolution) * self.resolution

    def prune(self, now: float):
        cut = now - self.horizon - self.resolution
        for k in [k for k in self.buckets if k < cut]:
            del self.buckets[k]

    def bucket(self, now: float, make: Callable[[], Any]):
        k = self._key(now)
        b = self.buckets.get(k)
        if b is None:
            b = self.buckets[k] = make()
            self.prune(now)
        return b

    def window(self, window_s: float, now: float) -> List[Any]:
        cut = now - float(window_s) - self.resolution
        return [b for k, b in self.buckets.items() if cut < k <= now]


class Objective:
    """One declarative service-level objective (module docstring).

    Use the constructors: :meth:`latency`, :meth:`ratio`, :meth:`floor`.
    ``windows``: burn-rate windows in seconds, longest first by
    convention; the alert condition must hold on ALL of them.
    ``burn_threshold``: the multiple of budget-spend-rate that alerts.
    ``for_s`` / ``clear_s`` / ``resolve_ratio``: the pending dwell,
    resolve dwell, and hysteresis band of the state machine."""

    def __init__(self, name: str, kind: str, target: float,
                 metric: Optional[str] = None,
                 bad: Optional[str] = None, total: Optional[str] = None,
                 compliance: float = 0.99,
                 windows: Tuple[float, ...] = (300.0, 60.0),
                 burn_threshold: float = 2.0, for_s: float = 30.0,
                 clear_s: float = 60.0, resolve_ratio: float = 0.9,
                 description: str = ""):
        if kind not in ("latency", "ratio", "floor"):
            raise ValueError(f"unknown objective kind {kind!r}")
        if kind in ("latency", "floor") and not metric:
            raise ValueError(f"{kind} objective needs a sample metric")
        if kind == "ratio" and not (bad and total):
            raise ValueError("ratio objective needs bad= and total= "
                             "counter names")
        if not 0.0 < compliance < 1.0:
            raise ValueError("compliance must be in (0, 1)")
        if not windows:
            raise ValueError("need at least one window")
        self.name = str(name)
        self.kind = kind
        self.target = float(target)
        self.metric = metric
        self.bad = bad
        self.total = total
        self.compliance = float(compliance)
        self.windows = tuple(float(w) for w in windows)
        self.burn_threshold = float(burn_threshold)
        self.for_s = float(for_s)
        self.clear_s = float(clear_s)
        self.resolve_ratio = float(resolve_ratio)
        self.description = description

    @property
    def budget(self) -> float:
        """Allowed bad fraction: the error budget burn rates divide by."""
        if self.kind == "ratio":
            return self.target
        return 1.0 - self.compliance

    @classmethod
    def latency(cls, name: str, metric: str, target_s: float,
                compliance: float = 0.99, **kw) -> "Objective":
        """p-quantile latency objective: at most ``1 - compliance`` of
        ``metric`` samples may exceed ``target_s`` (TTFT p99 ≤ 0.5s ==
        ``latency("ttft_p99", "ttft_s", 0.5, compliance=0.99)``)."""
        return cls(name, "latency", target_s, metric=metric,
                   compliance=compliance, **kw)

    @classmethod
    def ratio(cls, name: str, bad: str, total: str, target: float,
              **kw) -> "Objective":
        """Event-ratio objective: ``bad``/``total`` stays under
        ``target`` (shed rate, error rate)."""
        return cls(name, "ratio", target, bad=bad, total=total, **kw)

    @classmethod
    def floor(cls, name: str, metric: str, floor: float,
              compliance: float = 0.95, **kw) -> "Objective":
        """Gauge-floor objective: at most ``1 - compliance`` of
        ``metric`` samples may fall BELOW ``floor`` (the goodput
        floor)."""
        return cls(name, "floor", floor, metric=metric,
                   compliance=compliance, **kw)

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind,
                "target": self.target, "metric": self.metric,
                "bad": self.bad, "total": self.total,
                "compliance": self.compliance, "budget": self.budget,
                "windows_s": list(self.windows),
                "burn_threshold": self.burn_threshold,
                "for_s": self.for_s, "clear_s": self.clear_s,
                "resolve_ratio": self.resolve_ratio,
                "description": self.description}


class _AlertState:
    __slots__ = ("state", "since", "clear_since", "fired_at")

    def __init__(self):
        self.state = "inactive"
        self.since: Optional[float] = None
        self.clear_since: Optional[float] = None
        self.fired_at: Optional[float] = None


class SLOMonitor:
    """Declarative SLOs + multi-window burn-rate alerting (module
    docstring).  ``clock`` is injectable for deterministic tests;
    ``resolution_s``/``horizon_s`` bound the time-bucketed stores;
    ``tracer`` (a ``telemetry.Tracer``) receives alert transitions as
    ``slo`` ring events."""

    def __init__(self, objectives=(), *, clock: Callable[[], float] = None,
                 tracer=None, resolution_s: float = 5.0,
                 horizon_s: float = 3600.0, transition_history: int = 256,
                 logger: Optional[logging.Logger] = None):
        self._clock = clock if clock is not None else time.monotonic
        self.tracer = tracer
        self.resolution_s = float(resolution_s)
        self.horizon_s = float(horizon_s)
        self._lock = threading.Lock()
        # serializes evaluate()'s alert state machine: /slo and /metrics
        # handler threads may evaluate concurrently, and a half-applied
        # pending→cancelled transition must never be observable
        self._eval_lock = threading.Lock()
        self._samples: Dict[str, _TimeBuckets] = {}
        self._counters: Dict[str, _TimeBuckets] = {}
        self._objectives: Dict[str, Objective] = {}
        self._alerts: Dict[str, _AlertState] = {}
        self._transitions: collections.deque = collections.deque(
            maxlen=int(transition_history))
        # transition subscribers (autoscaler etc.): called with a COPY of
        # each transition event, outside the windowed-store lock but under
        # _eval_lock — a subscriber must never call back into evaluate()
        self._subscribers: List[Callable[[Dict[str, Any]], None]] = []
        self._ledgers: List[Any] = []
        self.registry = StatRegistry()
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        for obj in objectives:
            self.add_objective(obj)

    # ---------------------------------------------------------- config --

    def add_objective(self, obj: Objective) -> Objective:
        with self._lock:
            if obj.name in self._objectives:
                raise ValueError(f"objective {obj.name!r} already defined")
            self._objectives[obj.name] = obj
            self._alerts[obj.name] = _AlertState()
        return obj

    def objectives(self) -> List[Objective]:
        with self._lock:
            return list(self._objectives.values())

    def subscribe(self, fn: Callable[[Dict[str, Any]], None]):
        """Register a transition subscriber: ``fn(event)`` is called for
        every alert transition (``pending`` / ``firing`` / ``resolved`` /
        ``cancelled``) with a copy of the transition-history event — the
        push feed a controller (``autoscaler.ElasticAutoscaler``) closes
        its loop on.  Callbacks run under the evaluation lock, so a
        subscriber must NEVER call back into ``evaluate()``/``snapshot()``
        (deadlock); read the event, update your own state, return.  A
        raising subscriber is logged and skipped — it cannot take the
        evaluator down.  Returns ``fn`` (decorator-friendly)."""
        if not callable(fn):
            raise TypeError(f"subscriber must be callable, got {fn!r}")
        with self._lock:
            self._subscribers.append(fn)
        return fn

    def unsubscribe(self, fn) -> bool:
        with self._lock:
            try:
                self._subscribers.remove(fn)
                return True
            except ValueError:
                return False

    def alert_states(self) -> Dict[str, str]:
        """Current alert state per objective name (no evaluation pass —
        the states as of the last ``evaluate()``); what a late-attaching
        subscriber seeds itself from."""
        with self._lock:
            return {name: st.state for name, st in self._alerts.items()}

    def attach_ledger(self, ledger) -> "SLOMonitor":
        """Sample a ``telemetry_ledger.RunLedger``'s goodput gauge into
        the ``goodput`` metric at every ``evaluate()`` — the pull feed
        of the goodput-floor objective."""
        if not hasattr(ledger, "snapshot"):
            raise TypeError(f"not a ledger: {type(ledger).__name__}")
        with self._lock:
            self._ledgers.append(ledger)
        return self

    # ---------------------------------------------------------- ingest --

    def now(self) -> float:
        return self._clock()

    def observe(self, metric: str, value: float,
                now: Optional[float] = None):
        """Record one SAMPLE of ``metric`` (a latency, a gauge reading)
        into its time-bucketed sketch ring."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            tb = self._samples.get(metric)
            if tb is None:
                tb = self._samples[metric] = _TimeBuckets(
                    self.resolution_s, self.horizon_s)
            tb.bucket(now, PercentileSketch).add(float(value))

    def observe_sketch(self, metric: str, sketch: PercentileSketch,
                       now: Optional[float] = None):
        """Merge a whole sketch of samples into ``metric``'s time bucket
        at ``now`` — the federation ingest path: a fleet collector
        merges each target's CLOSED sketch buckets (exactly once) into
        its own series, so fleet-level burn rates are evaluated over
        true merged quantiles."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            tb = self._samples.get(metric)
            if tb is None:
                tb = self._samples[metric] = _TimeBuckets(
                    self.resolution_s, self.horizon_s)
            tb.bucket(now, lambda: PercentileSketch(
                alpha=sketch.alpha)).merge(sketch)

    def count(self, metric: str, n: int = 1, now: Optional[float] = None):
        """Record ``n`` EVENTS of ``metric`` (a counter increment) into
        its time-bucketed sum ring."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            tb = self._counters.get(metric)
            if tb is None:
                tb = self._counters[metric] = _TimeBuckets(
                    self.resolution_s, self.horizon_s)
            b = tb.bucket(now, lambda: [0.0])
            b[0] += float(n)

    # ------------------------------------------------------ window math --

    def _window_sketch(self, metric: str, window_s: float, now: float
                       ) -> PercentileSketch:
        out = PercentileSketch()
        tb = self._samples.get(metric)
        if tb is not None:
            for sk in tb.window(window_s, now):
                out.merge(sk)
        return out

    def _window_count(self, metric: str, window_s: float, now: float
                      ) -> float:
        tb = self._counters.get(metric)
        if tb is None:
            return 0.0
        return sum(b[0] for b in tb.window(window_s, now))

    def _bad_fraction(self, obj: Objective, window_s: float, now: float
                      ) -> Tuple[float, float]:
        """(bad_fraction, population) for one objective over one window.
        An empty window is (0, 0): no evidence, no alert."""
        if obj.kind == "ratio":
            total = self._window_count(obj.total, window_s, now)
            if total <= 0.0:
                return 0.0, 0.0
            bad = self._window_count(obj.bad, window_s, now)
            return bad / total, total
        sk = self._window_sketch(obj.metric, window_s, now)
        if sk.n == 0:
            return 0.0, 0.0
        if obj.kind == "latency":
            bad = sk.count_above(obj.target)
        else:                                   # floor: below target is bad
            bad = sk.n - sk.count_above(obj.target) - _at_or_near(
                sk, obj.target)
        return max(bad, 0) / sk.n, float(sk.n)

    def burn_rates(self, obj: Objective, now: Optional[float] = None
                   ) -> Dict[str, float]:
        """Burn rate per window: bad-fraction over the window divided by
        the objective's error budget."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            return {str(int(w)): self._bad_fraction(obj, w, now)[0]
                    / max(obj.budget, 1e-12)
                    for w in obj.windows}

    # -------------------------------------------------------- evaluate --

    def _transition(self, obj: Objective, st: _AlertState, what: str,
                    now: float, burns: Dict[str, float]):
        st.state = {"pending": "pending", "firing": "firing",
                    "resolved": "inactive",
                    "cancelled": "inactive"}[what]
        ev = {"what": what, "objective": obj.name, "ts": now,
              "burn": max(burns.values()) if burns else 0.0,
              "windows": dict(burns)}
        self._transitions.append(ev)
        self.registry.add(f"alerts_{what}")
        if self.tracer is not None:
            # the tracer stamps its OWN ring-relative ts — passing the
            # monitor's absolute clock through would corrupt the ring
            # timebase (and last_event_age_s/healthz liveness with it);
            # the monitor-clock reading rides along as ``at``
            self.tracer.emit("slo", at=now,
                             **{k: v for k, v in ev.items() if k != "ts"})
        log = (self._log.warning if what == "firing" else self._log.info)
        log("slo %s: %s (burn %.2f over windows %s)", what, obj.name,
            ev["burn"], list(obj.windows))
        with self._lock:
            subscribers = list(self._subscribers)
        for fn in subscribers:
            try:
                fn(dict(ev))
            except Exception:  # noqa: BLE001 — a broken subscriber must
                # not take the alert state machine down with it
                self._log.exception("slo: transition subscriber failed "
                                    "for %s %s", obj.name, what)

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Advance every objective's alert state machine to ``now`` and
        return the per-objective status rows (the core of
        ``snapshot()``).  Pull feeds (attached ledgers) are sampled
        first.  Idempotent for a fixed clock reading.  Serialized by
        ``_eval_lock`` — concurrent HTTP scrapes must not interleave a
        transition (``_lock`` alone guards the windowed stores, which
        observers keep feeding while an evaluation runs)."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            ledgers = list(self._ledgers)
        for led in ledgers:
            try:
                self.observe("goodput", float(led.snapshot()["goodput"]),
                             now=now)
            except Exception as e:  # noqa: BLE001 — a broken pull source
                # must not take the evaluator down
                self._log.debug("slo: ledger pull failed: %r", e)
        with self._eval_lock:
            return self._evaluate_locked(now)

    def _evaluate_locked(self, now: float) -> List[Dict[str, Any]]:
        rows = []
        with self._lock:
            objectives = list(self._objectives.values())
        for obj in objectives:
            with self._lock:
                fracs = {str(int(w)): self._bad_fraction(obj, w, now)
                         for w in obj.windows}
                st = self._alerts[obj.name]
            budget = max(obj.budget, 1e-12)
            burns = {k: f / budget for k, (f, _p) in fracs.items()}
            pops = {k: p for k, (_f, p) in fracs.items()}
            burning = all(b >= obj.burn_threshold for b in burns.values())
            cleared = all(b < obj.burn_threshold * obj.resolve_ratio
                          for b in burns.values())
            if burning:
                st.clear_since = None
                if st.state == "inactive":
                    st.since = now
                    self._transition(obj, st, "pending", now, burns)
                if st.state == "pending" and now - st.since >= obj.for_s:
                    st.fired_at = now
                    self._transition(obj, st, "firing", now, burns)
            elif st.state == "pending":
                # never fired: cancel quietly (still a recorded transition)
                st.since = None
                self._transition(obj, st, "cancelled", now, burns)
            elif st.state == "firing":
                # hysteresis: only a burn clearly below the threshold
                # (resolve_ratio band), sustained for clear_s, resolves —
                # hovering AT the boundary keeps the alert firing
                if cleared:
                    if st.clear_since is None:
                        st.clear_since = now
                    elif now - st.clear_since >= obj.clear_s:
                        st.since = st.clear_since = st.fired_at = None
                        self._transition(obj, st, "resolved", now, burns)
                else:
                    st.clear_since = None
            rows.append({
                "name": obj.name, "kind": obj.kind, "target": obj.target,
                "budget": obj.budget, "state": st.state,
                "since": st.since, "burn_rates": burns,
                "window_populations": pops,
                "burn_threshold": obj.burn_threshold,
                "sli": self._sli(obj, now),
            })
        return rows

    def _sli(self, obj: Objective, now: float) -> Optional[Dict[str, Any]]:
        """Current service-level indicator over the LONGEST window: the
        compliance quantile for latency/floor objectives, the rate for
        ratio ones."""
        w = max(obj.windows)
        with self._lock:
            if obj.kind == "ratio":
                total = self._window_count(obj.total, w, now)
                bad = self._window_count(obj.bad, w, now)
                return {"rate": (bad / total if total else None),
                        "bad": bad, "total": total}
            sk = self._window_sketch(obj.metric, w, now)
            return {"quantile": obj.compliance,
                    "value": sk.quantile(obj.compliance),
                    **sk.snapshot()}

    # --------------------------------------------------------- exports --

    def sketch_export(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Serialized per-time-bucket sample sketches, keyed by metric
        then bucket start (stringified for JSON) — the mergeable payload
        ``snapshot()`` ships as ``sketch_buckets`` for cross-process
        federation (``telemetry_fleet.FleetCollector``)."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            metrics = {}
            for name, tb in self._samples.items():
                tb.prune(now)
                if tb.buckets:
                    metrics[name] = {str(k): sk.to_dict()
                                     for k, sk in tb.buckets.items()}
        return {"resolution_s": self.resolution_s, "now": now,
                "metrics": metrics}

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /slo`` payload: objective definitions, live alert
        states and burn rates, SLIs, the recent transition ring, and the
        mergeable ``sketch_buckets`` export."""
        now = self._clock() if now is None else float(now)
        rows = self.evaluate(now)
        with self._eval_lock:
            transitions = list(self._transitions)
        return {
            "now": now,
            "objectives": [o.to_dict() for o in self.objectives()],
            "status": rows,
            "alerts_firing": sum(1 for r in rows
                                 if r["state"] == "firing"),
            "transitions": transitions,
            "sketch_buckets": self.sketch_export(now),
        }

    def prometheus_text(self, namespace: str = "paddle_tpu_slo") -> str:
        """Labeled burn-rate / alert-state / SLI gauges plus the
        transition counters — label values escaped through the shared
        ``utils.stats`` helper."""
        rows = self.evaluate()
        lines = [prometheus_text(self.registry, namespace=namespace)
                 .rstrip("\n")]
        lines.append(f"# TYPE {namespace}_burn_rate gauge")
        for r in rows:
            for w, b in r["burn_rates"].items():
                lines.append(prom_sample(
                    f"{namespace}_burn_rate", b,
                    {"objective": r["name"], "window_s": w}))
        lines.append(f"# TYPE {namespace}_alert_state gauge")
        for r in rows:
            lines.append(prom_sample(
                f"{namespace}_alert_state",
                ALERT_STATES.index(r["state"]),
                {"objective": r["name"]}))
        lines.append(f"# TYPE {namespace}_sli gauge")
        for r in rows:
            sli = r.get("sli") or {}
            v = sli.get("value", sli.get("rate"))
            if v is not None:
                lines.append(prom_sample(f"{namespace}_sli", v,
                                         {"objective": r["name"]}))
        return "\n".join(lines) + "\n"


def _at_or_near(sk: PercentileSketch, target: float) -> int:
    """Samples in the target's own bucket (treated as compliant for the
    floor objective — consistent with the sketch's alpha error band)."""
    if target <= 0.0 or sk.n == 0:
        return 0
    return sk.counts.get(sk._index(target), 0)
