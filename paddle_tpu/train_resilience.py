"""Crash-consistent training: two-phase-commit checkpoints, preemption-aware
saves, and a self-healing train supervisor.

The serving plane survives chaos (PR 12: fault injection + the gateway's
resilience layer); this module is the training-side twin.  Three parts:

**CheckpointManager** — versioned ``step-NNNNNNNN/`` directories over the
existing :mod:`paddle_tpu.distributed.checkpoint` writer, with a real
two-phase commit:

    write payload chunks → fsync every file → write ``ckpt.manifest.json``
    (per-file blake2b content digests + byte sizes + the process's
    ``sharding_rules_digest``) → fsync → atomic ``COMMIT`` marker LAST
    → fsync the directory

A step directory without a ``COMMIT`` marker never existed as far as
resolution is concerned; a step whose files disagree with the digests is
bitrot and is *skipped with a counted reason*, never loaded.  ``latest()``
therefore always answers "the newest step that is provably whole" — it
never loads garbage and never crashes on a half-written directory (the
SIGKILL-mid-save shape).  Retention is bounded (``retain`` newest committed
steps) with keep-every-N pinning for long-horizon rollback.  Async saves
ride the existing :class:`~paddle_tpu.distributed.checkpoint.SaveHandle`
(device→host snapshot is synchronous and attributes to the goodput
ledger's ``checkpoint_save`` bucket; digesting + commit chain on the same
background executor).

**PreemptionGuard** — a SIGTERM hook installed with the FlightRecorder
signal discipline (pinned bound-method handler identity, previous handler
saved): the handler only *requests* an emergency checkpoint; the
supervisor honors it at the next step boundary with a hard deadline
(``deadline_s``) — an emergency save that misses the deadline is abandoned
*uncommitted* (the prior committed step stays the resume point), then
:meth:`PreemptionGuard.release` chains the deferred previous handler so
the process dies exactly as it would have, just after the save window.

**TrainSupervisor** — wraps any ``make_*_train_step``-style loop: a step
that raises (injected ``alloc_fail``, a watchdog non-finite-loss
escalation, :class:`~paddle_tpu.faults.TransientDispatchError`) triggers
restore-from-last-good with exponential backoff and a bounded restart
budget; every decision is a ``train_resilience`` tracer event
(``save_commit`` / ``save_abandon`` / ``restore`` / ``restart`` /
``corrupt_skip`` / ``preempt_request`` / ``preempt_save`` / ``elastic_exit``)
plus ``paddle_tpu_train_resilience_*`` prometheus counters, and
``train_snapshot()`` feeds the ops server's ``GET /train`` route.
``elastic=`` plugs a :class:`~paddle_tpu.distributed.fleet.elastic
.ElasticManager` into the step boundary so world-size changes exit through
the same verified save path (resume reshards via ``sharding_rules`` — the
checkpoint layer loads into whatever mesh the relaunch compiles).

Bit-exact resume contract: a checkpoint bundles params, optimizer state
(including 1/R update-sharded shards from ``distributed/update_sharding``),
grad_comm ``comm_e`` error-feedback residual, the *base* RNG key + step
counter (per-step keys are re-derived via
:func:`paddle_tpu.jit.functional.fold_in_step_key`, a pure function of
both), and the data-iterator epoch/offset — so the resumed loss trajectory
equals the uninterrupted run's exactly.  docs/TRAINING_RESILIENCE.md walks
the protocol state machine and the runbook.

No reference counterpart: the reference's fleet/elastic checkpoints via
whole-program pickle with no commit marker, digest, or RNG capture.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import os
import shutil
import signal as _signal
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .distributed import checkpoint as _ckpt
from .distributed.checkpoint import CorruptCheckpoint
from .faults import (FaultInjectionError, FaultPlan, InjectedAllocationError,
                     TransientDispatchError)
from .faults import corrupt_file as _apply_corrupt_file
from .faults import torn_write as _apply_torn_write
from .utils.stats import StatRegistry

__all__ = ["CheckpointManager", "ManagedSaveHandle", "PreemptionGuard",
           "ResumableIterator", "TrainSupervisor", "CorruptCheckpoint",
           "NonFiniteLossError", "RestartBudgetExhausted",
           "pack_train_state", "unpack_train_state"]

_COMMIT = "COMMIT"
_MANAGER_MANIFEST = "ckpt.manifest.json"
_STEP_FMT = "step-{:08d}"
_FS_FAULT_KINDS = ("torn_write", "corrupt_file")


class NonFiniteLossError(FloatingPointError):
    """The numerics watchdog escalated: the loss came back NaN/Inf.  The
    supervisor raises this AFTER the step returned (the state is already
    poisoned) so the restore path rolls back to the last committed
    checkpoint instead of checkpointing the NaN forward."""


class RestartBudgetExhausted(RuntimeError):
    """The supervisor's bounded restart budget ran out — the failure is
    not transient; a human (or the launcher's own restart policy) has to
    decide.  Carries the last exception as ``__cause__``."""


# --------------------------------------------------------------------------
# small fs helpers
# --------------------------------------------------------------------------

def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _digest_file(path: str) -> str:
    h = hashlib.blake2b(digest_size=16)
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _step_dirname(step: int) -> str:
    return _STEP_FMT.format(int(step))


def _parse_step_dirname(name: str) -> Optional[int]:
    if not name.startswith("step-"):
        return None
    digits = name[len("step-"):]
    return int(digits) if digits.isdigit() else None


# --------------------------------------------------------------------------
# full-state bundling (what "everything needed for bit-exact resume" means)
# --------------------------------------------------------------------------

def _is_typed_key(key) -> bool:
    import jax
    try:
        return jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key)
    except (AttributeError, TypeError):
        return False


def pack_train_state(state, *, step: int, base_key=None,
                     data_state: Optional[Dict[str, int]] = None) -> Dict:
    """Bundle a functional TrainState plus the loop-side state a restart
    needs: the step counter, the *base* RNG key (per-step keys re-derive
    via ``fold_in_step_key``), and the data-iterator position.  Typed
    ``jax.random.key`` keys are stored as their ``key_data`` uint32 array
    (npy-serializable) with a flag to re-wrap on restore."""
    import jax
    bundle: Dict[str, Any] = {"train": state, "step": int(step)}
    if base_key is not None:
        typed = _is_typed_key(base_key)
        kd = jax.random.key_data(base_key) if typed else base_key
        bundle["rng"] = {"key_data": np.asarray(kd), "typed": bool(typed)}
    if data_state is not None:
        bundle["data"] = {k: int(v) for k, v in sorted(data_state.items())}
    return bundle


def unpack_train_state(bundle: Dict):
    """Inverse of :func:`pack_train_state`: returns
    ``(state, step, base_key, data_state)`` (key/data None when absent)."""
    import jax
    key = None
    if "rng" in bundle:
        kd = bundle["rng"]["key_data"]
        key = jax.random.wrap_key_data(np.asarray(kd).astype(np.uint32)) \
            if bundle["rng"]["typed"] else kd
    return (bundle["train"], int(bundle["step"]), key, bundle.get("data"))


# --------------------------------------------------------------------------
# CheckpointManager
# --------------------------------------------------------------------------

class ManagedSaveHandle:
    """Join handle for a managed (optionally async) save.  ``wait()``
    joins payload writes AND the commit phase; ``committed`` is the
    truth bit — False means the step was abandoned (torn payload, missed
    deadline, injected fault) and the previous committed step is still
    the resume point."""

    def __init__(self, step: int, path: str, future=None,
                 committed: bool = False):
        self.step = int(step)
        self.path = path
        self._future = future
        self._committed = bool(committed)

    def wait(self) -> bool:
        if self._future is not None:
            self._committed = bool(self._future.result())
            self._future = None
        return self._committed

    result = wait

    def done(self) -> bool:
        return self._future is None or self._future.done()

    @property
    def committed(self) -> bool:
        if self._future is not None and self._future.done():
            self.wait()
        return self._committed


class CheckpointManager:
    """Versioned two-phase-commit checkpoints under ``root`` (module
    docstring for the protocol).  ``fault_plan`` faults of kind
    ``torn_write``/``corrupt_file`` are consulted at save time with a
    **save-ordinal clock** (``Fault(at_s=2)`` hits the third save) —
    chaos tests drive the exact crash shapes through the same plan
    vocabulary as the serving faults."""

    def __init__(self, root: str, retain: int = 5,
                 keep_every: Optional[int] = None, tracer=None,
                 registry: Optional[StatRegistry] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 logger: Optional[logging.Logger] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.root = root
        os.makedirs(root, exist_ok=True)
        if int(retain) < 1:
            raise ValueError("retain must be >= 1")
        self.retain = int(retain)
        self.keep_every = None if keep_every is None else int(keep_every)
        self.tracer = tracer
        # guarded-by: none — StatRegistry serializes internally (per-stat
        # locks), safe from the async-commit pool thread
        self.registry = registry if registry is not None else StatRegistry()
        self.fault_plan = fault_plan
        self._mu = threading.Lock()
        self._fault_spent: Dict[int, int] = {}      # guarded-by: _mu
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._clock = clock
        self._save_ordinal = 0
        #: skip-reason counters ``latest()`` accumulates (each torn step
        #: is counted once per reason, not once per ``latest()`` call)
        self.skips: Dict[str, int] = {}             # guarded-by: _mu
        self._counted_skips: set = set()            # guarded-by: _mu
        self._inflight: Dict[int, ManagedSaveHandle] = {}  # guarded-by: _mu
        self.rules_mismatch_steps: List[int] = []   # guarded-by: _mu

    # ------------------------------------------------------------- paths --
    def step_path(self, step: int) -> str:
        return os.path.join(self.root, _step_dirname(step))

    def steps(self) -> List[int]:
        """Every step directory under root (committed or not), ascending."""
        out = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return out
        for name in names:
            step = _parse_step_dirname(name)
            if step is not None and os.path.isdir(os.path.join(self.root, name)):
                out.append(step)
        return sorted(out)

    def is_committed(self, step: int) -> bool:
        return os.path.exists(os.path.join(self.step_path(step), _COMMIT))

    # -------------------------------------------------------------- save --
    def save(self, bundle, step: int, *, async_save: bool = False,
             deadline_s: Optional[float] = None,
             meta: Optional[Dict] = None) -> ManagedSaveHandle:
        """Two-phase-commit save of ``bundle`` as step ``step``.

        Sync: returns with ``committed`` already known.  Async: payload
        snapshot is synchronous (rides ``distributed.checkpoint.save``'s
        ledger-attributed device→host copy); file writes + digest +
        commit chain on the checkpoint executor; ``wait()`` joins.
        ``deadline_s`` bounds the WHOLE save wall (emergency-save
        semantics): past it, the commit marker is withheld and the step
        abandoned."""
        step = int(step)
        path = self.step_path(step)
        if os.path.isdir(path):
            # re-save of a step (restart replay): the old dir — committed
            # or torn — is superseded; drop it so stale files can't mix in
            shutil.rmtree(path)
        t0 = self._clock()
        ordinal = self._save_ordinal
        self._save_ordinal += 1
        inner = _ckpt.save(bundle, path, async_save=async_save)
        if async_save:
            fut = _ckpt._get_executor().submit(
                self._commit, inner, path, step, t0, ordinal, deadline_s,
                meta)
            handle = ManagedSaveHandle(step, path, future=fut)
            with self._mu:
                self._inflight[step] = handle
            return handle
        committed = self._commit(inner, path, step, t0, ordinal,
                                 deadline_s, meta)
        return ManagedSaveHandle(step, path, committed=committed)

    def _commit(self, inner, path: str, step: int, t0: float, ordinal: int,
                deadline_s: Optional[float], meta: Optional[Dict]) -> bool:
        try:
            inner.wait()
        except Exception as e:  # noqa: BLE001 — payload failure of ANY
            # shape (disk full, injected) must abandon, not crash commit
            self._abandon(step, f"payload_error:{type(e).__name__}")
            return False
        if self._maybe_torn_write(path, step, ordinal):
            # the torn step stays UNCOMMITTED on disk — exactly what a
            # crash mid-payload leaves — so resolution must skip it
            self._abandon(step, "torn_write")
            return False
        try:
            digests = self._digest_payload(path)
        except OSError as e:
            self._abandon(step, f"digest_error:{type(e).__name__}")
            return False
        from .distributed.sharding_rules import sharding_rules_digest
        manifest = {"format": 1, "step": step, "files": digests,
                    "sharding_rules_digest": sharding_rules_digest(),
                    "meta": meta or {}}
        mtmp = os.path.join(path, _MANAGER_MANIFEST + ".tmp")
        with open(mtmp, "w") as f:
            json.dump(manifest, f)
        _fsync_path(mtmp)
        os.replace(mtmp, os.path.join(path, _MANAGER_MANIFEST))
        if deadline_s is not None and self._clock() - t0 > deadline_s:
            self._abandon(step, "deadline", deadline_s=deadline_s)
            return False
        ctmp = os.path.join(path, _COMMIT + ".tmp")
        with open(ctmp, "w") as f:
            json.dump({"step": step,
                       "manifest_blake2b": _digest_file(
                           os.path.join(path, _MANAGER_MANIFEST))}, f)
        _fsync_path(ctmp)
        os.replace(ctmp, os.path.join(path, _COMMIT))
        _fsync_path(path)
        with self._mu:
            self._inflight.pop(step, None)
        wall = self._clock() - t0
        nbytes = sum(rec["bytes"] for rec in digests.values())
        self.registry.add("saves_committed")
        self.registry.set("last_committed_step", step)
        self._emit("save_commit", step=step, wall_s=wall, bytes=nbytes,
                   files=len(digests))
        self._maybe_corrupt_file(path, step, ordinal)
        return True

    def _digest_payload(self, path: str) -> Dict[str, Dict]:
        out: Dict[str, Dict] = {}
        for fname in sorted(os.listdir(path)):
            if fname in (_COMMIT, _MANAGER_MANIFEST) or fname.endswith(".tmp"):
                continue
            fp = os.path.join(path, fname)
            _fsync_path(fp)
            out[fname] = {"blake2b": _digest_file(fp),
                          "bytes": os.path.getsize(fp)}
        return out

    def _abandon(self, step: int, reason: str, **fields):
        with self._mu:
            self._inflight.pop(step, None)
        self.registry.add("saves_abandoned")
        self._emit("save_abandon", step=step, reason=reason, **fields)
        self._log.warning("checkpoint step %d abandoned uncommitted (%s)",
                          step, reason)

    # ---------------------------------------------------------- fs faults --
    def _fs_fault(self, kind: str, ordinal: int):
        if self.fault_plan is None:
            return None
        for f in self.fault_plan.faults:
            if f.kind != kind or not f.active(float(ordinal)):
                continue
            if f.count is not None:
                with self._mu:
                    used = self._fault_spent.get(id(f), 0)
                    if used >= f.count:
                        continue
                    self._fault_spent[id(f)] = used + 1
            return f
        return None

    def _payload_files(self, path: str) -> List[str]:
        return sorted(f for f in os.listdir(path)
                      if f not in (_COMMIT, _MANAGER_MANIFEST)
                      and not f.endswith(".tmp") and f.endswith(".npy"))

    def _maybe_torn_write(self, path: str, step: int, ordinal: int) -> bool:
        fault = self._fs_fault("torn_write", ordinal)
        if fault is None:
            return False
        files = self._payload_files(path)
        if not files:
            return False
        rng = self.fault_plan.rng(f"ckpt:{ordinal}")
        victim = files[rng.randrange(len(files))]
        kept = _apply_torn_write(os.path.join(path, victim), rng)
        self._emit("fault_inject", fault="torn_write", step=step,
                   file=victim, kept_bytes=kept)
        return True

    def _maybe_corrupt_file(self, path: str, step: int, ordinal: int) -> None:
        fault = self._fs_fault("corrupt_file", ordinal)
        if fault is None:
            return
        files = self._payload_files(path)
        if not files:
            return
        rng = self.fault_plan.rng(f"ckpt:{ordinal}")
        victim = files[rng.randrange(len(files))]
        flipped = _apply_corrupt_file(os.path.join(path, victim), rng)
        self._emit("fault_inject", fault="corrupt_file", step=step,
                   file=victim, flipped_bytes=flipped)

    # -------------------------------------------------------- resolution --
    def verify(self, step: int) -> Tuple[bool, Optional[str]]:
        """Is step ``step`` provably whole?  ``(True, None)`` or
        ``(False, reason)`` with reason in ``uncommitted`` /
        ``bad_manifest`` / ``missing_file`` / ``size_mismatch`` /
        ``digest_mismatch``.  Never raises on a damaged directory."""
        path = self.step_path(step)
        if not os.path.exists(os.path.join(path, _COMMIT)):
            return False, "uncommitted"
        try:
            with open(os.path.join(path, _COMMIT)) as f:
                marker = json.load(f)
            with open(os.path.join(path, _MANAGER_MANIFEST)) as f:
                raw = f.read()
            manifest = json.loads(raw)
        except (OSError, ValueError):
            return False, "bad_manifest"
        want = marker.get("manifest_blake2b")
        if want is not None:
            h = hashlib.blake2b(digest_size=16)
            h.update(raw.encode())
            if h.hexdigest() != want:
                return False, "bad_manifest"
        for fname, rec in manifest.get("files", {}).items():
            fp = os.path.join(path, fname)
            try:
                size = os.path.getsize(fp)
            except OSError:
                return False, "missing_file"
            if size != rec["bytes"]:
                return False, "size_mismatch"
            if _digest_file(fp) != rec["blake2b"]:
                return False, "digest_mismatch"
        from .distributed.sharding_rules import sharding_rules_digest
        if manifest.get("sharding_rules_digest") != sharding_rules_digest() \
                and step not in self.rules_mismatch_steps:
            # NOT fatal: an elastic rescale / rule edit legitimately
            # resumes old checkpoints (resharding happens at load) — but
            # the operator should know the rules moved under the data
            with self._mu:
                self.rules_mismatch_steps.append(step)
            self._emit("rules_mismatch", step=step)
            self._log.warning(
                "checkpoint step %d was saved under different sharding "
                "rules (resume reshards via the current rules)", step)
        return True, None

    def latest(self, verify: bool = True) -> Optional[int]:
        """The newest step that is provably whole (or merely COMMIT-marked
        with ``verify=False``).  Torn/corrupt/uncommitted steps are
        skipped with a counted reason — never loaded, never raised on."""
        for step in reversed(self.steps()):
            if verify:
                ok, reason = self.verify(step)
            else:
                ok = self.is_committed(step)
                reason = None if ok else "uncommitted"
            if ok:
                return step
            self._count_skip(step, reason)
        return None

    def _count_skip(self, step: int, reason: str) -> None:
        with self._mu:
            if (step, reason) in self._counted_skips:
                return
            self._counted_skips.add((step, reason))
            self.skips[reason] = self.skips.get(reason, 0) + 1
        self.registry.add("corrupt_skips")
        self._emit("corrupt_skip", step=step, reason=reason)
        self._log.warning("skipping checkpoint step %d (%s)", step, reason)

    def restore(self, target, step: Optional[int] = None, shardings=None):
        """Load step ``step`` (default: :meth:`latest`) into ``target``'s
        tree structure; returns ``(step, bundle)``.  Raises
        :class:`CorruptCheckpoint` when an explicit step fails
        verification or no valid step exists — latest-resolution itself
        never loads garbage."""
        if step is None:
            step = self.latest()
            if step is None:
                raise CorruptCheckpoint(
                    f"no committed+verified checkpoint under {self.root!r} "
                    f"(skips so far: {self.skips})")
        else:
            ok, reason = self.verify(step)
            if not ok:
                raise CorruptCheckpoint(
                    f"checkpoint step {step} fails verification: {reason}")
        bundle = _ckpt.load(self.step_path(step), target=target,
                            shardings=shardings)
        self.registry.add("restores")
        self._emit("restore", step=step)
        return step, bundle

    # ---------------------------------------------------------- retention --
    def gc(self) -> List[int]:
        """Bounded retention: keep the ``retain`` newest committed steps
        plus every ``keep_every``-pinned committed step; delete older
        committed steps and any uncommitted junk strictly older than the
        newest committed step (abandoned dirs newer than it may be an
        in-flight save — untouched).  Returns the removed steps."""
        steps = self.steps()
        committed = [s for s in steps if self.is_committed(s)]
        if not committed:
            return []
        newest = committed[-1]
        keep = set(committed[-self.retain:])
        if self.keep_every:
            keep.update(s for s in committed if s % self.keep_every == 0)
        removed = []
        for s in steps:
            if s in keep or s >= newest:
                continue
            with self._mu:
                handle = self._inflight.get(s)
            if handle is not None and not handle.done():
                continue
            shutil.rmtree(self.step_path(s), ignore_errors=True)
            removed.append(s)
        if removed:
            self._emit("gc", removed=len(removed), newest=newest)
        return removed

    # ------------------------------------------------------------ plumbing --
    def _emit(self, what: str, **fields) -> None:
        if self.tracer is not None:
            self.tracer.emit("train_resilience", what=what, **fields)

    def snapshot(self) -> Dict[str, Any]:
        reg = self.registry
        return {"root": self.root,
                "steps": self.steps(),
                "latest_committed": self.latest(verify=False),
                "saves_committed": int(reg.value("saves_committed")),
                "saves_abandoned": int(reg.value("saves_abandoned")),
                "corrupt_skips": int(reg.value("corrupt_skips")),
                "restores": int(reg.value("restores")),
                "skips": dict(self.skips),
                "retain": self.retain, "keep_every": self.keep_every}


# --------------------------------------------------------------------------
# preemption
# --------------------------------------------------------------------------

class PreemptionGuard:
    """SIGTERM → "emergency checkpoint at the next step boundary".

    Installed with the FlightRecorder signal discipline: the bound-method
    handler identity is pinned at construction, the previous handler is
    saved, and uninstall only restores when the slot still holds OUR
    handler.  The chain is *deferred*, not dropped: the handler merely
    records the request; after the supervisor's emergency save,
    :meth:`release` re-delivers to the previous handler (FlightRecorder's
    dump-then-die, or the default action) so the process terminates
    exactly as the signal intended — just after the save window.
    ``request()`` is the imperative twin for tests and benches."""

    def __init__(self, signals: Sequence[int] = (_signal.SIGTERM,),
                 tracer=None, logger: Optional[logging.Logger] = None):
        self.signals = tuple(signals)
        self.tracer = tracer
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._handler = self._on_signal  # pinned bound-method identity
        self._prev: Dict[int, Any] = {}
        self._installed = False
        self._requested = False
        self._signum: Optional[int] = None

    def install(self) -> "PreemptionGuard":
        for s in self.signals:
            self._prev[s] = _signal.getsignal(s)
            _signal.signal(s, self._handler)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s in self.signals:
            if _signal.getsignal(s) is self._handler:
                _signal.signal(s, self._prev.get(s, _signal.SIG_DFL))
        self._installed = False

    def _on_signal(self, signum, frame) -> None:
        self._requested = True
        self._signum = signum
        if self.tracer is not None:
            self.tracer.emit("train_resilience", what="preempt_request",
                             signum=int(signum))
        self._log.warning(
            "signal %d: emergency checkpoint requested at next step "
            "boundary", signum)

    def request(self) -> None:
        """Imperative preemption request (the deterministic
        SIGTERM-equivalent benches and tests use)."""
        self._requested = True

    @property
    def requested(self) -> bool:
        return self._requested

    def reset(self) -> None:
        self._requested = False
        self._signum = None

    def release(self) -> None:
        """Chain the deferred signal to the previous handler (call after
        the emergency save).  No-op when the request was imperative."""
        signum = self._signum
        self.uninstall()
        if signum is None:
            return
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, None)
        elif prev == _signal.SIG_DFL:
            _signal.signal(signum, _signal.SIG_DFL)
            os.kill(os.getpid(), signum)


# --------------------------------------------------------------------------
# resumable data
# --------------------------------------------------------------------------

class ResumableIterator:
    """Deterministic, seekable batch stream over an indexable dataset:
    ``(epoch, offset)`` IS the whole iteration state, so a checkpoint
    stores two ints and ``seek()`` replays from exactly the same batch —
    the data half of the bit-exact resume contract."""

    def __init__(self, batches: Sequence):
        if len(batches) == 0:
            raise ValueError("ResumableIterator needs at least one batch")
        self._batches = batches
        self.epoch = 0
        self.offset = 0

    def state(self) -> Dict[str, int]:
        return {"epoch": self.epoch, "offset": self.offset}

    def seek(self, state: Dict[str, int]) -> None:
        self.epoch = int(state.get("epoch", 0))
        self.offset = int(state.get("offset", 0)) % len(self._batches)

    def next_batch(self):
        batch = self._batches[self.offset]
        self.offset += 1
        if self.offset >= len(self._batches):
            self.offset = 0
            self.epoch += 1
        return batch

    def __len__(self):
        return len(self._batches)


# --------------------------------------------------------------------------
# TrainSupervisor
# --------------------------------------------------------------------------

class TrainSupervisor:
    """Self-healing driver around a functional train step (module
    docstring).  ``step_fn`` follows the :func:`make_train_step` shape
    ``step(state, key, lr, inputs, labels) -> (state, (loss, out))`` by
    default; pass ``call=`` to adapt any other ``make_*_train_step``
    signature: ``call(step_fn, state, key_t, lr, batch) -> (state, loss)``.

    Recovery: a retryable step exception restores the last committed
    checkpoint, sleeps an exponential backoff, and replays — at most
    ``restart_budget`` times (then :class:`RestartBudgetExhausted`).
    A checkpoint is always taken at the resume point before the first
    step so a last-good exists even for a step-0 failure.  ``fault_plan``
    drives deterministic chaos with a **step-valued clock**
    (``Fault("alloc_fail", at_s=7, count=1)`` fires before step 7)."""

    #: step exceptions the restore path absorbs (everything else is a
    #: structural bug and propagates — budget or not)
    RETRYABLE = (FaultInjectionError, TransientDispatchError, MemoryError,
                 NonFiniteLossError)

    def __init__(self, step_fn, state, manager: CheckpointManager, *,
                 base_key=None, lr: float = 1e-2,
                 data: Optional[ResumableIterator] = None,
                 call: Optional[Callable] = None,
                 save_every: int = 50, async_save: bool = False,
                 restart_budget: int = 3, backoff_s: float = 0.5,
                 backoff_factor: float = 2.0, backoff_max_s: float = 30.0,
                 escalate_non_finite: bool = True,
                 guard: Optional[PreemptionGuard] = None,
                 emergency_deadline_s: float = 30.0,
                 elastic=None, elastic_exit: Callable[[int], Any] = sys.exit,
                 fault_plan: Optional[FaultPlan] = None,
                 shardings=None, tracer=None,
                 registry: Optional[StatRegistry] = None,
                 logger: Optional[logging.Logger] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 on_boundary: Optional[Callable[[int, "TrainSupervisor"],
                                               None]] = None):
        self.step_fn = step_fn
        self.state = state
        self.manager = manager
        self.base_key = base_key
        self.lr = lr
        self.data = data
        self._call = call if call is not None else self._default_call
        self.save_every = int(save_every)
        self.async_save = bool(async_save)
        self.restart_budget = int(restart_budget)
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.backoff_max_s = float(backoff_max_s)
        self.escalate_non_finite = bool(escalate_non_finite)
        self.guard = guard
        self.emergency_deadline_s = emergency_deadline_s
        self.elastic = elastic
        self._elastic_exit = elastic_exit
        self.fault_plan = fault_plan
        self._fault_spent: Dict[int, int] = {}
        self.shardings = shardings
        self.tracer = tracer if tracer is not None else manager.tracer
        if manager.tracer is None:
            manager.tracer = self.tracer
        self.registry = registry if registry is not None else manager.registry
        self._log = logger if logger is not None \
            else logging.getLogger(__name__)
        self._clock = clock
        self._sleep = sleep
        self.on_boundary = on_boundary
        self._step = 0
        self._status = "idle"
        self._restarts = 0
        self._steps_replayed = 0
        self._recovery_s = 0.0
        self._last_handle: Optional[ManagedSaveHandle] = None
        self._last_loss: Optional[float] = None
        self._preempted = False

    # -------------------------------------------------------- step adapter --
    @staticmethod
    def _default_call(step_fn, state, key_t, lr, batch):
        out = step_fn(state, key_t, lr, *batch)
        state, aux = out
        loss = aux[0] if isinstance(aux, (tuple, list)) else aux
        return state, loss

    # ------------------------------------------------------------- bundling --
    def _bundle(self, step: int) -> Dict:
        return pack_train_state(
            self.state, step=step, base_key=self.base_key,
            data_state=self.data.state() if self.data is not None else None)

    def _shardings_bundle(self, template: Dict):
        if self.shardings is None:
            return None
        return {"train": self.shardings}

    # ----------------------------------------------------------- save/restore
    def _save(self, step: int, *, sync: bool = False,
              deadline_s: Optional[float] = None) -> ManagedSaveHandle:
        if self._last_handle is not None and not self._last_handle.done():
            # one async save in flight at a time: joining here bounds
            # dirty-state lag and keeps save ordinals deterministic
            if self._last_handle.wait():
                self.manager.gc()
        handle = self.manager.save(
            self._bundle(step), step,
            async_save=self.async_save and not sync,
            deadline_s=deadline_s)
        self._last_handle = handle
        if not self.async_save or sync:
            committed = handle.wait()
            if committed:
                self.manager.gc()
        return handle

    def _restore(self) -> int:
        t0 = self._clock()
        template = self._bundle(self._step)
        step, bundle = self.manager.restore(
            template, shardings=self._shardings_bundle(template))
        state, t, key, data_state = unpack_train_state(bundle)
        self.state = state
        if key is not None:
            self.base_key = key
        if self.data is not None and data_state is not None:
            self.data.seek(data_state)
        self.registry.set("last_restored_step", step)
        self._recovery_s += self._clock() - t0
        return t

    # -------------------------------------------------------------- chaos --
    def _maybe_inject(self, t: int) -> None:
        if self.fault_plan is None:
            return
        for f in self.fault_plan.faults:
            if f.kind not in ("alloc_fail", "dispatch_error") \
                    or not f.active(float(t)):
                continue
            if f.count is not None:
                used = self._fault_spent.get(id(f), 0)
                if used >= f.count:
                    continue
                self._fault_spent[id(f)] = used + 1
            self._emit("fault_inject", fault=f.kind, step=t)
            if f.kind == "alloc_fail":
                raise InjectedAllocationError(
                    f"injected allocation failure (step {t})")
            raise TransientDispatchError(
                f"injected dispatch failure (step {t})")

    # ---------------------------------------------------------------- run --
    def run(self, num_steps: int, resume: bool = True) -> Dict[str, Any]:
        """Drive ``num_steps`` total steps (counting from step 0 of the
        run's life, not from the resume point) and return the result
        record.  On entry, resumes from the newest verified checkpoint
        when one exists; otherwise seeds a step-0 checkpoint so a
        last-good always exists."""
        t = 0
        if resume and self.manager.latest() is not None:
            t = self._restore()
        else:
            self._save(t, sync=True)
        self._status = "running"
        self._preempted = False
        loss_by_step: Dict[int, float] = {}
        t0_run = t
        while t < int(num_steps):
            self._step = t
            self.registry.set("step", t)
            try:
                self._maybe_inject(t)
                key_t = None
                if self.base_key is not None:
                    from .jit.functional import fold_in_step_key
                    key_t = fold_in_step_key(self.base_key, t)
                batch = self.data.next_batch() if self.data is not None \
                    else ()
                state, loss = self._call(self.step_fn, self.state, key_t,
                                         self.lr, batch)
                loss_f = float(loss)
                if self.escalate_non_finite and not math.isfinite(loss_f):
                    raise NonFiniteLossError(
                        f"watchdog escalation: non-finite loss at step {t}")
            except self.RETRYABLE as e:
                t = self._recover(t, e)
                # replayed steps overwrite their loss entries, so the
                # trajectory stays one value per step (the bit-exact
                # oracle comparison depends on this)
                for done in [s for s in loss_by_step if s >= t]:
                    del loss_by_step[done]
                continue
            self.state = state
            self._last_loss = loss_f
            loss_by_step[t] = loss_f
            t += 1
            self._step = t
            self.registry.set("steps_done", t)
            if self.on_boundary is not None:
                self.on_boundary(t, self)
            if self.guard is not None and self.guard.requested:
                self._emergency(t, "preempt")
                self._status = "preempted"
                self._preempted = True
                self.guard.release()
                break
            if self.elastic is not None:
                code = self.elastic.exit_code()
                if code is not None:
                    self._emergency(t, f"elastic:{code}")
                    self._emit("elastic_exit", step=t, code=int(code))
                    self._status = "rescaling"
                    self._elastic_exit(code)
                    break  # reached only when elastic_exit doesn't exit
            if self.save_every and t % self.save_every == 0:
                self._save(t)
        if self._last_handle is not None and self._last_handle.wait():
            self.manager.gc()
        if self._status == "running":
            self._status = "done"
        result = {"completed": self._status == "done",
                  "preempted": self._preempted,
                  "final_step": t,
                  "first_step": t0_run,
                  "final_loss": self._last_loss,
                  "losses": [loss_by_step[s] for s in sorted(loss_by_step)],
                  "restarts": self._restarts,
                  "steps_replayed": self._steps_replayed,
                  "recovery_time_s": self._recovery_s,
                  "skips": dict(self.manager.skips)}
        self.result = result
        return result

    def _recover(self, t: int, exc: BaseException) -> int:
        if self._restarts >= self.restart_budget:
            # the failed attempt does NOT count as a restart — the budget
            # bounds restore+replay cycles, and this one never restores
            self._status = "failed"
            self._emit("give_up", step=t, restarts=self._restarts,
                       error=type(exc).__name__)
            raise RestartBudgetExhausted(
                f"restart budget ({self.restart_budget}) exhausted at "
                f"step {t}") from exc
        self._restarts += 1
        self.registry.add("restarts")
        self._emit("restart", step=t, error=type(exc).__name__,
                   restarts=self._restarts)
        self._log.warning("step %d raised %s — restart %d/%d", t,
                          type(exc).__name__, self._restarts,
                          self.restart_budget)
        backoff = min(self.backoff_s *
                      self.backoff_factor ** (self._restarts - 1),
                      self.backoff_max_s)
        self._sleep(backoff)
        if self._last_handle is not None:
            self._last_handle.wait()  # an in-flight save may be last-good
        restored = self._restore()
        self._steps_replayed += max(0, t - restored)
        self.registry.add("steps_replayed", max(0, t - restored))
        return restored

    def _emergency(self, t: int, reason: str) -> None:
        handle = self._save(t, sync=True,
                            deadline_s=self.emergency_deadline_s)
        self.registry.add("preemptions")
        self._emit("preempt_save", step=t, committed=handle.committed,
                   reason=reason)

    # ------------------------------------------------------------ surfaces --
    def _emit(self, what: str, **fields) -> None:
        if self.tracer is not None:
            self.tracer.emit("train_resilience", what=what, **fields)

    def train_snapshot(self) -> Dict[str, Any]:
        """The ops-server surface behind ``GET /train``."""
        reg = self.registry
        return {"status": self._status,
                "step": self._step,
                "last_loss": self._last_loss,
                "restarts": self._restarts,
                "restart_budget": self.restart_budget,
                "steps_replayed": self._steps_replayed,
                "recovery_time_s": self._recovery_s,
                "preempted": self._preempted,
                "saves_committed": int(reg.value("saves_committed")),
                "saves_abandoned": int(reg.value("saves_abandoned")),
                "corrupt_skips": int(reg.value("corrupt_skips")),
                "restores": int(reg.value("restores")),
                "checkpoint": self.manager.snapshot()}

    def prometheus_text(self) -> str:
        from .utils.stats import prometheus_text
        return prometheus_text(self.registry,
                               namespace="paddle_tpu_train_resilience")
