"""Model summary (reference: python/paddle/hapi/model_summary.py)."""

from __future__ import annotations

import numpy as np

from ..core.dtype import get_default_dtype


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    total_params = 0
    trainable = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = sum(int(np.prod(p.shape)) for p in layer._parameters.values()
                       if p is not None)
        if not n_params and layer._sub_layers:
            continue
        total = sum(int(np.prod(p.shape)) for _, p in layer.named_parameters())
        rows.append((name or layer.__class__.__name__, layer.__class__.__name__,
                     n_params))
    for _, p in net.named_parameters():
        n = int(np.prod(p.shape))
        total_params += n
        if p.trainable:
            trainable += n
    width = max([len(r[0]) for r in rows] + [10])
    lines = [f"{'Layer':<{width}}  {'Type':<24}  Params"]
    lines.append("-" * (width + 34))
    for name, typ, n in rows:
        lines.append(f"{name:<{width}}  {typ:<24}  {n:,}")
    lines.append("-" * (width + 34))
    lines.append(f"Total params: {total_params:,}")
    lines.append(f"Trainable params: {trainable:,}")
    lines.append(f"Non-trainable params: {total_params - trainable:,}")
    print("\n".join(lines))
    return {"total_params": total_params, "trainable_params": trainable}
