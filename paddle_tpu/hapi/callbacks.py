"""hapi callbacks (reference: python/paddle/hapi/callbacks.py — ProgBarLogger
:297, ModelCheckpoint :533, LRScheduler :598, EarlyStopping :689, VisualDL
:843, ReduceLROnPlateau :958)."""

from __future__ import annotations

import numbers
import os
import time
from typing import List, Optional


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = callbacks if callbacks is not None else []
    cbks = cbks if isinstance(cbks, (list, tuple)) else [cbks]
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + list(cbks)
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = [LRScheduler(by_step=True, by_epoch=False)] + list(cbks)
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = list(cbks) + [ModelCheckpoint(save_freq, save_dir)]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"batch_size": batch_size, "epochs": epochs, "steps": steps,
                    "verbose": verbose, "metrics": metrics or []})
    return lst


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __iter__(self):
        return iter(self.callbacks)

    def _call(self, name, *args):
        for c in self.callbacks:
            fn = getattr(c, name, None)
            if fn:
                fn(*args)

    def on_begin(self, mode, logs=None):
        self._call(f"on_{mode}_begin", logs or {})

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs or {})

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs or {})

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs or {})

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs or {})

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs or {})


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.step = 0

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                parts.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, tuple)):
                parts.append(f"{k}: " + "/".join(f"{x:.4f}" for x in v))
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        self.step += 1
        if self.verbose >= 2 and self.step % self.log_freq == 0:
            steps = self.params.get("steps")
            print(f"Epoch {self.epoch + 1}/{self.epochs} step {self.step}/{steps}"
                  f" - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.epochs} - {self._fmt(logs)}"
                  f" - {time.time() - self._t0:.1f}s")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step like the reference default)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step, self.by_epoch = by_step, by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class ManagedCheckpoint(Callback):
    """Crash-consistent twin of :class:`ModelCheckpoint`: epoch saves go
    through a ``train_resilience.CheckpointManager`` two-phase commit
    (digest manifest + COMMIT marker, bounded retention), and
    ``on_train_begin`` restores the newest *verified* checkpoint — a kill
    mid-save can never leave ``fit`` resuming from torn state, which the
    plain ``model.save`` path cannot promise.

    The bundle is the model's functional TrainState (params, optimizer
    slots, buffers — AMP scaler state included when configured) plus the
    epoch index; restored weights are synced back into the eager Layer so
    ``model.network`` agrees with the resumed state.  Epoch numbering is
    the step index, so ``manager.keep_every`` pins every N-th epoch.
    ``resumed_epoch`` reports where training picked up (the fit loop
    still drives its own epoch range; skip-ahead is the caller's call)."""

    def __init__(self, manager, save_freq: int = 1, resume: bool = True):
        super().__init__()
        self.manager = manager
        self.save_freq = max(1, int(save_freq))
        self.resume = resume
        self.resumed_epoch = None

    def on_train_begin(self, logs=None):
        if not self.resume or self.manager.latest() is None:
            return
        self.model._ensure_train_step()
        template = {"train": self.model._state, "epoch": 0}
        epoch, bundle = self.manager.restore(template)
        self.model._state = bundle["train"]
        from ..jit.functional import sync_state_to_layer
        sync_state_to_layer(self.model.network, self.model._state)
        self.resumed_epoch = int(bundle["epoch"])

    def on_epoch_end(self, epoch, logs=None):
        if epoch % self.save_freq != 0 or self.model._state is None:
            return
        bundle = {"train": self.model._state, "epoch": int(epoch)}
        self.manager.save(bundle, epoch).wait()
        self.manager.gc()

    def on_train_end(self, logs=None):
        if self.model._state is None:
            return
        final = {"train": self.model._state,
                 "epoch": int(self.params.get("epochs", 0))}
        self.manager.save(final, self.params.get("epochs", 0)).wait()
        self.manager.gc()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.stopped_epoch = 0
        self.wait = 0
        self.best = None
        self.stop_training = False

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                self.stop_training = True


class VisualDL(Callback):
    """Scalar logger; writes TSV (VisualDL itself is external to this image)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        with open(os.path.join(self.log_dir, "scalars.tsv"), "a") as f:
            for k, v in (logs or {}).items():
                if isinstance(v, numbers.Number):
                    f.write(f"{self._step}\t{k}\t{v}\n")


class TelemetryCallback(Callback):
    """Training telemetry for ``Model.fit`` — attaches a
    ``paddle_tpu.telemetry.TrainMonitor`` to the model so every train batch
    records host wall / device-blocked time, examples/sec, tokens/sec, and
    the numerics watchdog rides the loss values ``fit`` already fetches at
    ``log_freq`` (no extra device syncs).  While training runs the monitor
    is also installed process-wide (``telemetry.set_active_monitor``) so
    AMP GradScaler found_inf/scale events and ``Profiler.step`` timings
    land in the same trace.

    ``hbm_every=N`` takes a live-array HBM census every N epochs (0 = only
    at train end); ``jsonl_path``/``chrome_path`` dump the event trace at
    train end (the JSONL merges into a device trace via
    ``tools/trace_to_chrome.py --engine-trace``); ``aggregate_on_end``
    (default: only when world>1) all-reduces the step counters across
    hosts and emits the global-throughput/straggler event.
    Without this callback, ``Model`` pays one attribute check per step.
    """

    def __init__(self, monitor=None, hbm_every: int = 0,
                 jsonl_path: Optional[str] = None,
                 chrome_path: Optional[str] = None,
                 aggregate_on_end: Optional[bool] = None):
        super().__init__()
        if monitor is None:
            from ..telemetry import TrainMonitor
            monitor = TrainMonitor()
        self.monitor = monitor
        self.hbm_every = int(hbm_every)
        self.jsonl_path = jsonl_path
        self.chrome_path = chrome_path
        self.aggregate_on_end = aggregate_on_end
        self.last_aggregate = None
        self._prev_active = None

    def set_model(self, model):
        super().set_model(model)
        model._monitor = self.monitor

    def _census(self):
        state = getattr(self.model, "_state", None) or {}
        self.monitor.hbm_census(params=state.get("params"),
                                opt=state.get("opt"))

    def on_train_begin(self, logs=None):
        from ..telemetry import set_active_monitor
        self._prev_active = set_active_monitor(self.monitor)

    def on_epoch_end(self, epoch, logs=None):
        if self.hbm_every and (epoch + 1) % self.hbm_every == 0:
            self._census()

    def on_train_end(self, logs=None):
        import logging
        from ..telemetry import set_active_monitor
        log = logging.getLogger(__name__)
        try:
            self._census()
            agg = self.aggregate_on_end
            if agg is None:
                from ..distributed import env
                agg = env.get_world_size() > 1
            if agg:
                try:
                    self.last_aggregate = self.monitor.aggregate()
                except RuntimeError as e:
                    # eager cross-process collectives are unsupported on
                    # some topologies (collective.py all_reduce contract) —
                    # telemetry must never abort a finished training run
                    log.warning("telemetry aggregation skipped: %s", e)
            if self.jsonl_path:
                self.monitor.dump_jsonl(self.jsonl_path)
            if self.chrome_path:
                self.monitor.write_chrome_trace(self.chrome_path)
        finally:
            # symmetric teardown even if a census/dump raised: restore the
            # process-wide monitor and detach from the model so a later
            # fit() WITHOUT this callback is back to one attr check
            set_active_monitor(self._prev_active)
            if self.model is not None \
                    and getattr(self.model, "_monitor", None) is self.monitor:
                self.model._monitor = None


class GoodputCallback(Callback):
    """Goodput/badput wall-clock attribution for ``Model.fit``
    (docs/OBSERVABILITY.md, Goodput section).

    Wraps the fit window in a ``telemetry_ledger.RunLedger``: at train
    begin the ledger is (re)started and installed process-wide
    (``set_active_ledger``) so the DataLoader (``data_wait``), checkpoint
    I/O (``checkpoint_save``/``checkpoint_restore``) and fleet-metric
    collective (``comm``) seams report; a ``TrainMonitor`` is ensured on
    the model (reusing an existing one — e.g. ``TelemetryCallback``'s —
    or creating its own) and its event stream forwards into the ledger,
    so the hapi blocked-loss-fetch split feeds ``compute`` vs
    ``host_dispatch`` and first-dispatch walls feed ``compile``;
    ``Model.evaluate`` runs land in ``eval``.

    At train end ``last_snapshot`` freezes the attribution (buckets sum
    to the fit window's elapsed wall; ``goodput = compute/elapsed``), one
    INFO line summarizes it, ``json_path`` optionally dumps the full
    payload (mergeable into a device trace via ``tools/trace_to_chrome.py
    --ledger``), and every hook is restored — a later fit without this
    callback is back to one attribute check per step.

    ``ops_server=``: an ``ops_server.OpsServer`` to attach the ledger
    (and monitor) to, making ``/ledger`` and the ledger gauges in
    ``/metrics`` live during training.
    """

    def __init__(self, ledger=None, monitor=None, json_path=None,
                 ops_server=None):
        super().__init__()
        if ledger is None:
            from ..telemetry_ledger import RunLedger
            ledger = RunLedger()
            self._own_ledger = True
        else:
            self._own_ledger = False
        self.ledger = ledger
        self._monitor_arg = monitor
        self.monitor = None
        self.json_path = json_path
        self.ops_server = ops_server
        self.last_snapshot = None
        self._own_monitor = False

    def on_train_begin(self, logs=None):
        if self._own_ledger:
            # elapsed must measure the fit window, not construction-to-fit
            # dead time; a caller-provided ledger keeps its own clock (it
            # may span several fits deliberately)
            self.ledger.reset()
        mon = getattr(self.model, "_monitor", None)
        if mon is None:
            if self._monitor_arg is None:
                from ..telemetry import TrainMonitor
                mon = TrainMonitor()
            else:
                mon = self._monitor_arg
            self.model._monitor = mon
            self._own_monitor = True
        self.monitor = mon
        mon.set_ledger(self.ledger)
        self.ledger.activate()
        if self.ops_server is not None:
            self.ops_server.attach(self.ledger, name="fit-ledger")
            self.ops_server.attach(mon, name="fit-monitor")

    def on_train_end(self, logs=None):
        import logging
        try:
            self.last_snapshot = snap = self.ledger.snapshot()
            fr = snap["fractions"]
            logging.getLogger(__name__).info(
                "goodput %.3f over %.2fs wall (compute %.1f%%, data_wait "
                "%.1f%%, host_dispatch %.1f%%, compile %.1f%%, "
                "unattributed %.1f%%)",
                snap["goodput"], snap["elapsed_s"],
                100 * fr["compute"], 100 * fr["data_wait"],
                100 * fr["host_dispatch"], 100 * fr["compile"],
                100 * fr["unattributed"])
            if self.json_path:
                self.ledger.dump_json(self.json_path)
        finally:
            # symmetric teardown (the TelemetryCallback convention): detach
            # the ledger from the monitor and the active slot, and drop an
            # own monitor so a later fit pays one attribute check again
            self.ledger.deactivate()
            mon = self.monitor
            if mon is not None:
                mon.set_ledger(None)
                if self._own_monitor \
                        and getattr(self.model, "_monitor", None) is mon:
                    self.model._monitor = None
            self._own_monitor = False


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.mode = "min" if mode == "auto" and "loss" in monitor else mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.wait = 0
        self.cooldown_counter = 0
        self.best = None

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        better = self.best is None or (cur < self.best - self.min_delta
                                       if self.mode == "min"
                                       else cur > self.best + self.min_delta)
        if better:
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter > 0:
            self.cooldown_counter -= 1
        else:
            self.wait += 1
            if self.wait >= self.patience:
                opt = self.model._optimizer
                from ..optimizer.lr import LRScheduler as Sched
                if not isinstance(opt._learning_rate, Sched):
                    new_lr = max(opt.get_lr() * self.factor, self.min_lr)
                    opt.set_lr(new_lr)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr -> {new_lr}")
                self.cooldown_counter = self.cooldown
                self.wait = 0
