"""FLOPs counter (reference: python/paddle/hapi/dynamic_flops.py)."""

from __future__ import annotations

import numpy as np

import jax

from ..core.tensor import Tensor


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count FLOPs by tracing the jitted forward and summing XLA cost
    analysis — strictly more accurate than the reference's per-layer hooks."""
    from ..jit.functional import functionalize
    apply_fn, params, buffers = functionalize(net)
    x = jax.ShapeDtypeStruct(tuple(input_size), jax.numpy.float32)

    def f(p, b, xx):
        out, _ = apply_fn(p, b, xx, training=False)
        return out

    lowered = jax.jit(f).lower(
        jax.tree_util.tree_map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
        jax.tree_util.tree_map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers),
        x)
    try:
        cost = lowered.compile().cost_analysis()
        fl = cost.get("flops", 0.0) if isinstance(cost, dict) else cost[0].get("flops", 0.0)
    except Exception as e:
        # warn loudly instead of silently reporting 0 FLOPs as a measurement
        # (round-1 verdict: the bare `except: fl=0.0` hid failures)
        import warnings
        warnings.warn(f"XLA cost analysis unavailable: {e!r}; returning 0")
        fl = 0.0
    if print_detail:
        print(f"Total FLOPs: {fl:,.0f}")
    return int(fl)
