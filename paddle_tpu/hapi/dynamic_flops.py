"""FLOPs counter (reference: python/paddle/hapi/dynamic_flops.py)."""

from __future__ import annotations

import numpy as np

import jax

from ..core.tensor import Tensor


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count FLOPs by tracing the jitted forward and summing XLA cost
    analysis — strictly more accurate than the reference's per-layer hooks."""
    from ..jit.functional import functionalize
    apply_fn, params, buffers = functionalize(net)
    x = jax.ShapeDtypeStruct(tuple(input_size), jax.numpy.float32)

    def f(p, b, xx):
        out, _ = apply_fn(p, b, xx, training=False)
        return out

    lowered = jax.jit(f).lower(
        jax.tree_util.tree_map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
        jax.tree_util.tree_map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers),
        x)
    try:
        cost = lowered.compile().cost_analysis()
        fl = cost.get("flops", 0.0) if isinstance(cost, dict) else cost[0].get("flops", 0.0)
    except Exception:
        fl = 0.0
    if print_detail:
        print(f"Total FLOPs: {fl:,.0f}")
    return int(fl)
