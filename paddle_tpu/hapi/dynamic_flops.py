"""FLOPs counter (reference: python/paddle/hapi/dynamic_flops.py)."""

from __future__ import annotations

import numpy as np

import jax

from ..core.tensor import Tensor

#: lowered-program digest -> XLA cost-analysis flops.  flops() used to
#: re-lower and re-COMPILE the whole model on every call (a multi-second
#: stall for a one-number query); keyed on the lowered StableHLO text the
#: cache is config-sensitive by construction (stride/padding/activation
#: changes alter the program even when param shapes match), and only the
#: compile — the expensive part — is skipped on a hit.
_COST_CACHE: dict = {}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count FLOPs by tracing the jitted forward and summing XLA cost
    analysis — strictly more accurate than the reference's per-layer hooks.
    Every call re-lowers (cheap, and the source of the cache key); the
    compile + cost_analysis result is cached per lowered program
    (see _COST_CACHE)."""
    from ..jit.aot import fingerprint
    from ..jit.functional import functionalize
    apply_fn, params, buffers = functionalize(net)
    x = jax.ShapeDtypeStruct(tuple(input_size), jax.numpy.float32)

    def f(p, b, xx):
        out, _ = apply_fn(p, b, xx, training=False)
        return out

    lowered = jax.jit(f).lower(
        jax.tree_util.tree_map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
        jax.tree_util.tree_map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers),
        x)
    key = fingerprint("hapi_flops", lowered.as_text())
    fl = _COST_CACHE.get(key)
    if fl is None:
        try:
            cost = lowered.compile().cost_analysis()
            fl = cost.get("flops", 0.0) if isinstance(cost, dict) else cost[0].get("flops", 0.0)
            _COST_CACHE[key] = fl
        except Exception as e:
            # warn loudly instead of silently reporting 0 FLOPs as a
            # measurement (round-1 verdict: the bare `except: fl=0.0` hid
            # failures) — and never cache the failure, so a recovered
            # backend re-measures
            import warnings
            warnings.warn(f"XLA cost analysis unavailable: {e!r}; returning 0")
            fl = 0.0
    if print_detail:
        print(f"Total FLOPs: {fl:,.0f}")
    return int(fl)
