"""FLOPs counter (reference: python/paddle/hapi/dynamic_flops.py)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import jax

from ..core.tensor import Tensor

#: lowered-program digest -> {"flops", "bytes"} from XLA cost analysis.
#: flops() used to re-lower and re-COMPILE the whole model on every call
#: (a multi-second stall for a one-number query); keyed on the lowered
#: StableHLO text the cache is config-sensitive by construction (stride/
#: padding/activation changes alter the program even when param shapes
#: match), and only the compile — the expensive part — is skipped on a
#: hit.  Shared by flops(), the serving engines' compile-seam cost
#: attribution (telemetry MFU), and jit/aot.compile_aot.
_COST_CACHE: dict = {}


def _normalize_cost(cost) -> Dict[str, float]:
    """XLA ``cost_analysis()`` output (a dict, or a list of per-device
    dicts) -> {"flops", "bytes"} floats (missing keys -> 0.0)."""
    if not isinstance(cost, dict):
        cost = cost[0] if cost else {}
    return {"flops": float(cost.get("flops", 0.0) or 0.0),
            "bytes": float(cost.get("bytes accessed", 0.0) or 0.0)}


def cost_of_lowered(lowered, warn: bool = False,
                    allow_compile: bool = True
                    ) -> Optional[Dict[str, float]]:
    """``{"flops", "bytes"}`` for a ``jax.stages.Lowered`` program via
    ``lowered.compile().cost_analysis()``, cached per lowered-program
    digest — one compile per distinct program PER PROCESS, every later
    query is a dict lookup.  Returns None when cost analysis is
    unavailable (never cached, so a recovered backend re-measures);
    ``warn=True`` surfaces the failure as a warning (flops() does — a
    silent 0 is a lie to the caller).  ``allow_compile=False`` answers
    from the cache only (the aot warm/disk paths, which must not pay a
    compile just to label an event)."""
    from ..jit.aot import fingerprint
    key = fingerprint("hapi_cost", lowered.as_text())
    cached = _COST_CACHE.get(key)
    if cached is not None:
        return dict(cached)
    if not allow_compile:
        return None
    try:
        cost = _normalize_cost(lowered.compile().cost_analysis())
    except Exception as e:  # noqa: BLE001 — cost attribution is
        # best-effort telemetry; the caller decides how loudly to fail
        if warn:
            import warnings
            warnings.warn(f"XLA cost analysis unavailable: {e!r}")
        return None
    _COST_CACHE[key] = cost
    return dict(cost)


def cost_of_compiled(compiled, lowered=None) -> Optional[Dict[str, float]]:
    """``{"flops", "bytes"}`` from an ALREADY-compiled executable —
    ``cost_analysis()`` on it is free (no extra compile).  When the
    ``lowered`` program is passed alongside, the result also seeds the
    digest cache so later ``cost_of_lowered`` queries (a second engine,
    ``flops()``) skip their compile.  None when unavailable; never
    raises."""
    try:
        cost = _normalize_cost(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 — best-effort telemetry only
        return None
    if lowered is not None:
        try:
            from ..jit.aot import fingerprint
            _COST_CACHE[fingerprint("hapi_cost", lowered.as_text())] = \
                dict(cost)
        except Exception as e:  # noqa: BLE001 — seeding is an
            # optimization; the measured cost is still returned
            import logging
            logging.getLogger(__name__).debug(
                "cost-cache seeding failed: %r", e)
    return cost


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count FLOPs by tracing the jitted forward and summing XLA cost
    analysis — strictly more accurate than the reference's per-layer hooks.
    Every call re-lowers (cheap, and the source of the cache key); the
    compile + cost_analysis result is cached per lowered program
    (see _COST_CACHE / cost_of_lowered)."""
    from ..jit.functional import functionalize
    apply_fn, params, buffers = functionalize(net)
    x = jax.ShapeDtypeStruct(tuple(input_size), jax.numpy.float32)

    def f(p, b, xx):
        out, _ = apply_fn(p, b, xx, training=False)
        return out

    lowered = jax.jit(f).lower(
        jax.tree_util.tree_map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
        jax.tree_util.tree_map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers),
        x)
    cost = cost_of_lowered(lowered, warn=True)
    fl = 0.0 if cost is None else cost["flops"]
    if print_detail:
        print(f"Total FLOPs: {fl:,.0f}")
    return int(fl)
