"""hapi Model (reference: python/paddle/hapi/model.py:906 — fit:1556,
evaluate:1786, predict:1889).

TPU-native: there is ONE adapter, not two (Dynamic/StaticGraphAdapter in the
reference) — the jit-compiled functional train step serves both roles.  The
step program (fwd+bwd+optimizer) is compiled once per input shape and state
flows through a donated pytree, so steady-state training has zero Python
per-op overhead.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import jax
import numpy as np

from ..core import rng
from ..core.tensor import Tensor
from ..jit.functional import (make_eval_step, make_train_step, sync_state_to_layer,
                              unwrap_tree)
from .callbacks import config_callbacks


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self._eval_step = None
        self._state = None
        self.stop_training = False
        # telemetry.TrainMonitor attached by hapi.callbacks.TelemetryCallback
        # (or set directly); None keeps the hot path at ONE attribute check
        self._monitor = None

    # ------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        metrics = metrics or []
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        self._amp_configs = amp_configs
        return self

    _SCALER_KEYS = ("init_loss_scaling", "incr_ratio", "decr_ratio",
                    "incr_every_n_steps", "decr_every_n_nan_or_inf")

    def _amp_cfg(self):
        cfg = self._amp_configs
        if not cfg:
            return None
        if isinstance(cfg, str):
            cfg = {"level": cfg}
        if cfg.get("level", "O1") == "O0":
            return None  # O0 = pure fp32, AMP off (reference semantics)
        return cfg

    def _amp_trace_ctx(self):
        """Context factory for tracing under AMP — jax.jit traces lazily at
        the first step call, so the auto_cast must wrap the traced body, not
        the step construction (reference hapi amp integration; bf16-first)."""
        cfg = self._amp_cfg()
        if cfg is None:
            return None
        def ctx():
            from .. import amp as _amp
            return _amp.auto_cast(
                enable=True, level=cfg.get("level", "O1"),
                dtype=cfg.get("dtype", "bfloat16"),
                custom_white_list=cfg.get("custom_white_list"),
                custom_black_list=cfg.get("custom_black_list"))
        return ctx

    def _ensure_train_step(self):
        if self._train_step is None:
            cfg = self._amp_cfg()
            if (cfg is not None and cfg.get("level") == "O2"
                    and not getattr(self, "_amp_decorated", False)):
                # O2 = whole-model low-precision params (norms stay fp32);
                # the optimizer keeps fp32 masters via multi_precision
                from .. import amp as _amp
                _amp.decorate(self.network, level="O2",
                              dtype=cfg.get("dtype", "bfloat16"))
                if cfg.get("master_weight", True):
                    self._optimizer._multi_precision = True
                self._amp_decorated = True
            scaler_cfg = None
            if cfg is not None and (cfg.get("dtype") == "float16" or
                                    any(k in cfg for k in self._SCALER_KEYS)):
                scaler_cfg = {k: cfg[k] for k in self._SCALER_KEYS if k in cfg}
                scaler_cfg.setdefault("init_loss_scaling", 2.0 ** 15)
            accum = getattr(self, "_accum_batches", 1)
            if accum > 1:
                if scaler_cfg:
                    raise NotImplementedError(
                        "loss scaling with accumulate_grad_batches>1 is not "
                        "wired yet; use bf16 AMP (no scaler) or accumulation=1")
                from ..jit.functional import make_accum_train_step
                self._train_step, self._state = make_accum_train_step(
                    self.network, self._loss, self._optimizer, accum,
                    trace_ctx=self._amp_trace_ctx())
            else:
                self._train_step, self._state = make_train_step(
                    self.network, self._loss, self._optimizer,
                    trace_ctx=self._amp_trace_ctx(), scaler_cfg=scaler_cfg)

    def _ensure_eval_step(self):
        if self._eval_step is None:
            self._eval_step = make_eval_step(self.network, self._loss)

    # ---------------------------------------------------------------- steps
    def _train_batch_device(self, inputs, labels=None):
        """One step WITHOUT host synchronization: returns the device loss.
        Metrics (if configured) still update per batch — computing them on
        host is their contract; with no metrics the step chain stays fully
        async (the round-1 fit loop synced every batch, serializing device
        and host — reference streams at log_freq via callbacks)."""
        first_call = self._train_step is None
        self._ensure_train_step()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        raw_in = unwrap_tree(list(inputs))
        raw_lab = unwrap_tree(list(labels)) if labels is not None else []
        key = rng.next_key()
        lr = np.float32(self._optimizer.get_lr())
        mon = self._monitor           # the one telemetry check per step
        t0 = time.perf_counter() if mon is not None else 0.0
        self._state, (loss, out) = self._train_step(self._state, key, lr, raw_in, raw_lab)
        if mon is not None:
            wall = time.perf_counter() - t0
            if first_call:
                # jit traces+compiles inside the first dispatch — record it
                # as the compile event (first-dispatch wall; execution stays
                # async, no block added), keeping step percentiles steady-
                # state like instrument_train_step's convention
                mon.record_compile(("hapi_step",), wall)
            else:
                lead = getattr(raw_in[0], "shape", (0,)) if raw_in else (0,)
                mon.record_step(wall, trainer="hapi",
                                examples=int(lead[0]) if lead else 0,
                                tokens=int(lead[0] * lead[1])
                                if len(lead) == 2 else 0)
        self._optimizer._step_count += 1
        for m in self._metrics:
            m.update(m.compute(Tensor(out), *[Tensor(l) for l in raw_lab]),
                     *[Tensor(l) for l in raw_lab])
        return loss

    def train_batch(self, inputs, labels=None, update=True):
        loss_dev = self._train_batch_device(inputs, labels)
        t0 = time.perf_counter()
        val = float(np.asarray(loss_dev))
        mon = self._monitor
        if mon is not None:     # watchdog rides the fetch that just happened
            mon.record_sync(time.perf_counter() - t0, loss=val)
        return [val]

    def eval_batch(self, inputs, labels=None):
        self._ensure_eval_step()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        raw_in = unwrap_tree(list(inputs))
        raw_lab = unwrap_tree(list(labels)) if labels is not None else None
        if self._state is None:
            params, buffers = self.network.raw_state()
            state = {"params": params, "buffers": buffers}
        else:
            state = self._state
        out, loss = self._eval_step(state["params"], state["buffers"], raw_in, raw_lab)
        return out, (None if loss is None else float(np.asarray(loss)))

    def predict_batch(self, inputs):
        out, _ = self.eval_batch(inputs)
        return [np.asarray(out)]

    # ----------------------------------------------------------------- fit
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset
        # Dataset-only wrapping (reference model.py:1708 contract: a plain
        # list is iterated as a loader of already-collated batches)
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        eval_loader = None
        if eval_data is not None:
            eval_loader = DataLoader(eval_data, batch_size=batch_size) \
                if isinstance(eval_data, Dataset) else eval_data

        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        accumulate_grad_batches = max(1, int(accumulate_grad_batches))
        if self._train_step is not None \
                and getattr(self, "_accum_batches", 1) != accumulate_grad_batches:
            # rebuild on ANY window change (incl. back to 1): sync trained
            # params to the layer first and carry the optimizer state, else
            # the rebuild would silently reset Adam moments / trained weights
            self._sync_back()
            old_opt = self._state["opt"] if self._state is not None else None
            self._train_step = None
            self._accum_batches = accumulate_grad_batches
            self._ensure_train_step()
            if old_opt is not None:
                self._state["opt"] = old_opt
        self._accum_batches = accumulate_grad_batches
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                epochs=epochs, steps=steps, log_freq=log_freq,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir, metrics=self._metrics_name())
        self.stop_training = False
        cbks.on_begin("train")
        it = 0
        try:
            for epoch in range(epochs):
                cbks.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                loss_dev, loss_val = None, None
                train_iter = iter(train_loader)
                try:
                    for step, batch in enumerate(train_iter):
                        cbks.on_batch_begin("train", step)
                        inputs, labels = self._split_batch(batch)
                        loss_dev = self._train_batch_device(inputs, labels)
                        # host sync only at log_freq cadence — between log points
                        # the step chain stays async on device (loss in logs is
                        # the value at the last sync point, like the reference's
                        # streamed logs)
                        if step % log_freq == 0 or (num_iters is not None and
                                                    it + 1 >= num_iters):
                            t_sync = time.perf_counter()
                            # tpulint: disable=blocking-fetch-in-loop(the canonical allowed fetch: log_freq-cadence only, and telemetry measures it as THE device-blocked sync)
                            loss_val = float(np.asarray(loss_dev))
                            mon = self._monitor
                            if mon is not None:   # device-blocked wait + watchdog
                                mon.record_sync(time.perf_counter() - t_sync,
                                                loss=loss_val)
                        logs = {"loss": loss_val}
                        for m in self._metrics:
                            logs[self._m_name(m)] = m.accumulate()
                        logs["lr"] = self._optimizer.get_lr()
                        cbks.on_batch_end("train", step, logs)
                        it += 1
                        if num_iters is not None and it >= num_iters:
                            self.stop_training = True
                            break
                finally:
                    close = getattr(train_iter, "close", None)
                    if close is not None:  # release mp workers on early break
                        close()
                if loss_dev is not None:  # epoch-end logs carry the true last loss
                    # tpulint: disable=blocking-fetch-in-loop(once per EPOCH, not per step — the epoch-end log contract)
                    logs["loss"] = float(np.asarray(loss_dev))
                cbks.on_epoch_end(epoch, logs)
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_loader, verbose=verbose,
                                              callbacks=cbks)
                    cbks._call("on_eval_end", eval_logs)
                if self.stop_training:
                    break
        finally:
            mon = self._monitor
            if mon is not None:
                # whether training finished or raised, a callback-managed
                # monitor (it is the process-wide active one) must not leak
                # into later fits or the active slot; a raise skips
                # TelemetryCallback.on_train_end entirely, so this is the
                # only guaranteed teardown.  A manually-attached monitor
                # (never installed as active) is left alone.
                from ..telemetry import current_monitor, set_active_monitor
                if current_monitor() is mon:
                    set_active_monitor(None)
                    self._monitor = None
                # same guarantee for a GoodputCallback ledger: if THIS
                # fit's monitor feeds the process-wide active ledger, a
                # raise must not leave it installed (the callback's
                # on_train_end never runs on that path)
                from ..telemetry_ledger import (current_ledger,
                                                set_active_ledger)
                led = getattr(mon.tracer, "_ledger", None) \
                    if hasattr(mon, "tracer") else None
                if led is not None and current_ledger() is led:
                    set_active_ledger(None)
                    mon.set_ledger(None)
        cbks.on_end("train", logs)
        self._sync_back()
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        # goodput: the whole evaluation is one EXCLUSIVE ``eval`` span on
        # the active ledger — its inner data waits and loss fetches are
        # eval time, not data_wait/compute (double-attribution would break
        # the buckets-sum-to-elapsed invariant)
        from ..telemetry_ledger import ledger_span
        with ledger_span("eval", exclusive=True):
            return self._evaluate_impl(eval_data, batch_size, log_freq,
                                       verbose, num_workers, callbacks,
                                       num_samples)

    def _evaluate_impl(self, eval_data, batch_size, log_freq, verbose,
                       num_workers, callbacks, num_samples):
        from ..io import DataLoader, Dataset
        loader = DataLoader(eval_data, batch_size=batch_size) \
            if isinstance(eval_data, Dataset) else eval_data
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            inputs, labels = self._split_batch(batch)
            out, loss = self.eval_batch(inputs, labels)
            if loss is not None:
                losses.append(loss)
            for m in self._metrics:
                raw_lab = [getattr(l, "_data", l) for l in (labels or [])]
                m.update(m.compute(Tensor(out), *[Tensor(l) for l in raw_lab]),
                         *[Tensor(l) for l in raw_lab])
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[self._m_name(m)] = m.accumulate()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        from ..io import DataLoader, Dataset
        loader = DataLoader(test_data, batch_size=batch_size) \
            if isinstance(test_data, Dataset) else test_data
        outputs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(inputs)[0])
        if stack_outputs:
            return [np.concatenate(outputs, axis=0)]
        return [outputs]

    # --------------------------------------------------------------- helpers
    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)):
            n_in = len(self._inputs) if self._inputs else 1
            inputs = list(batch[:n_in])
            labels = list(batch[n_in:]) or None
            return inputs, labels
        return [batch], None

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            names.append(self._m_name(m))
        return names

    def _m_name(self, m):
        n = m.name()
        return n if isinstance(n, str) else n[0]

    def _sync_back(self):
        if self._state is not None:
            sync_state_to_layer(self.network, self._state)

    # ----------------------------------------------------------------- io
    def save(self, path, training=True):
        self._sync_back()
        from ..framework import io as fio
        fio.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fio.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import io as fio
        state = fio.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(fio.load(opt_path))
        # invalidate compiled state so new weights take effect
        self._train_step = None
        self._state = None
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        return summary(self.network, input_size, dtype)
