"""Text datasets (reference: python/paddle/text/datasets/*).

Each class parses the same archive format the reference downloads
(imdb.py, imikolov.py, uci_housing.py, ...) but from an explicit local
path — this build is zero-egress, so there is no download helper; pass
``data_file=`` (the archive or extracted file the reference's downloader
would have fetched).  All classes are map-style ``io.Dataset``s compatible
with DataLoader.
"""

from __future__ import annotations

import gzip
import os
import re
import tarfile
from typing import List, Optional

import numpy as np

from ...io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Conll05st", "Movielens",
           "WMT14", "WMT16"]


def _require(data_file, cls):
    if data_file is None or not os.path.exists(data_file):
        raise ValueError(
            f"{cls} requires a local data_file (zero-egress build has no "
            f"downloader). Supply the same archive the reference downloads; "
            f"got data_file={data_file!r}")
    return data_file


def _tokenize(line: str) -> List[str]:
    return re.sub(r"[^a-z0-9\s]", "", line.lower()).split()


class Imdb(Dataset):
    """IMDB movie-review sentiment (reference text/datasets/imdb.py).

    Parses the aclImdb tar (train/{pos,neg}/*.txt) into (word-id sequence,
    label) pairs with a frequency-cutoff vocabulary, like the reference's
    build_dict + tokenize pipeline.
    """

    def __init__(self, data_file=None, mode="train", cutoff=150):
        _require(data_file, "Imdb")
        self.mode = mode
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        # vocabulary spans BOTH splits (reference imdb.py build_dict runs on
        # train+test) so train/test word ids agree
        vocab_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(data_file) as tf:
            members = [m for m in tf.getmembers() if vocab_pat.match(m.name)]
            members.sort(key=lambda m: m.name)
            for m in members:
                text = tf.extractfile(m).read().decode("utf-8", "ignore")
                toks = _tokenize(text)
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
                if pat.match(m.name):
                    docs.append(toks)
                    labels.append(0 if "/pos/" in m.name else 1)
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
                 if c >= cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(t, unk) for t in d], np.int64)
                     for d in docs]
        self.labels = np.array(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-format n-gram language-model dataset (reference imikolov.py).

    data_type="NGRAM" yields (w0..w{N-2}, w{N-1}) windows; "SEQ" yields
    (input sequence, shifted target sequence) pairs.
    """

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        _require(data_file, "Imikolov")
        name = {"train": "ptb.train.txt", "valid": "ptb.valid.txt",
                "test": "ptb.test.txt"}[mode]
        lines = self._read(data_file, name)
        train_lines = lines if mode == "train" else \
            self._read(data_file, "ptb.train.txt")
        freq = {}
        for ln in train_lines:
            for w in ln.split():
                freq[w] = freq.get(w, 0) + 1
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
                 if c >= min_word_freq and w != "<unk>"]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        self.word_idx.setdefault("<s>", len(self.word_idx))
        self.word_idx.setdefault("<e>", len(self.word_idx))
        unk = self.word_idx["<unk>"]
        self.data = []
        for ln in lines:
            ids = ([self.word_idx["<s>"]]
                   + [self.word_idx.get(w, unk) for w in ln.split()]
                   + [self.word_idx["<e>"]])
            if data_type.upper() == "NGRAM":
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        self.data.append(
                            np.array(ids[i - window_size:i], np.int64))
            else:
                self.data.append((np.array(ids[:-1], np.int64),
                                  np.array(ids[1:], np.int64)))

    @staticmethod
    def _read(data_file, name):
        if tarfile.is_tarfile(data_file):
            with tarfile.open(data_file) as tf:
                member = next(m for m in tf.getmembers()
                              if m.name.endswith(name))
                return tf.extractfile(member).read().decode().splitlines()
        return open(data_file).read().splitlines()

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """Boston housing regression table (reference uci_housing.py):
    13 normalized features → price."""

    FEATURE_NUM = 14

    def __init__(self, data_file=None, mode="train"):
        _require(data_file, "UCIHousing")
        opener = gzip.open if data_file.endswith(".gz") else open
        with opener(data_file, "rt") as f:
            rows = [[float(v) for v in ln.split()] for ln in f
                    if ln.strip()]
        data = np.array(rows, np.float32)
        if data.shape[1] != self.FEATURE_NUM:
            raise ValueError(f"expected {self.FEATURE_NUM} columns, "
                             f"got {data.shape[1]}")
        feats = data[:, :-1]
        maxs, mins, avgs = feats.max(0), feats.min(0), feats.mean(0)
        feats = (feats - avgs) / np.maximum(maxs - mins, 1e-6)
        split = int(data.shape[0] * 0.8)
        if mode == "train":
            self.x, self.y = feats[:split], data[:split, -1:]
        else:
            self.x, self.y = feats[split:], data[split:, -1:]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Movielens(Dataset):
    """MovieLens-1M rating triples (reference movielens.py): parses
    ratings.dat (`user::movie::rating::ts`) from the ml-1m zip/dir."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        _require(data_file, "Movielens")
        lines = self._read(data_file, "ratings.dat")
        triples = []
        for ln in lines:
            parts = ln.strip().split("::")
            if len(parts) >= 3:
                triples.append((int(parts[0]), int(parts[1]), float(parts[2])))
        rng = np.random.RandomState(rand_seed)
        mask = rng.rand(len(triples)) < test_ratio
        keep = mask if mode == "test" else ~mask
        self.data = [t for t, k in zip(triples, keep) if k]

    @staticmethod
    def _read(data_file, name):
        if os.path.isdir(data_file):
            return open(os.path.join(data_file, name),
                        encoding="latin1").read().splitlines()
        import zipfile
        if zipfile.is_zipfile(data_file):
            with zipfile.ZipFile(data_file) as zf:
                member = next(n for n in zf.namelist() if n.endswith(name))
                return zf.read(member).decode("latin1").splitlines()
        return open(data_file, encoding="latin1").read().splitlines()

    def __getitem__(self, idx):
        u, m, r = self.data[idx]
        return (np.int64(u), np.int64(m), np.float32(r))

    def __len__(self):
        return len(self.data)


class _ParallelCorpus(Dataset):
    """Shared machinery for WMT14/WMT16: tab- or ``|||``-separated parallel
    lines → (src ids, trg ids, trg_next ids) with per-side vocabularies."""

    def __init__(self, data_file, mode, src_dict_size, trg_dict_size, cls,
                 swap_sides=False):
        _require(data_file, cls)
        pairs = []
        opener = gzip.open if str(data_file).endswith(".gz") else open
        with opener(data_file, "rt", encoding="utf-8", errors="ignore") as f:
            for ln in f:
                if "\t" in ln:
                    s, t = ln.rstrip("\n").split("\t")[:2]
                elif "|||" in ln:
                    s, t = ln.rstrip("\n").split("|||")[:2]
                else:
                    continue
                if swap_sides:
                    s, t = t, s
                pairs.append((s.split(), t.split()))
        self.src_dict = self._build_dict([p[0] for p in pairs], src_dict_size)
        self.trg_dict = self._build_dict([p[1] for p in pairs], trg_dict_size)
        s_unk, t_unk = self.src_dict["<unk>"], self.trg_dict["<unk>"]
        st, en = self.trg_dict["<s>"], self.trg_dict["<e>"]
        self.data = []
        for s, t in pairs:
            sid = np.array([self.src_dict.get(w, s_unk) for w in s], np.int64)
            tid = [self.trg_dict.get(w, t_unk) for w in t]
            self.data.append((sid, np.array([st] + tid, np.int64),
                              np.array(tid + [en], np.int64)))

    @staticmethod
    def _build_dict(corpus, size):
        freq = {}
        for words in corpus:
            for w in words:
                freq[w] = freq.get(w, 0) + 1
        vocab = ["<s>", "<e>", "<unk>"] + \
            [w for w, _ in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))]
        vocab = vocab[:size]
        return {w: i for i, w in enumerate(vocab)}

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class WMT14(_ParallelCorpus):
    """WMT14 en-fr translation pairs (reference wmt14.py)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000):
        super().__init__(data_file, mode, dict_size, dict_size, "WMT14")


class WMT16(_ParallelCorpus):
    """WMT16 en-de translation pairs (reference wmt16.py).  ``lang`` selects
    the source side: "en" keeps the file's (en, de) order, "de" swaps so
    German is the source (the reference's trg_lang knob, inverted)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en"):
        super().__init__(data_file, mode, src_dict_size, trg_dict_size, "WMT16",
                         swap_sides=(lang != "en"))


class Conll05st(Dataset):
    """CoNLL-2005 SRL dataset (reference conll05.py): parses the
    column-format props/words files from a local directory or tar.  The
    vocabulary spans all sentences; ``mode`` takes the leading 80% as train
    and the rest as test (the reference ships separate files per split —
    with one local file, split deterministically)."""

    def __init__(self, data_file=None, mode="train"):
        _require(data_file, "Conll05st")
        lines = Imikolov._read(data_file, "words.txt") \
            if not os.path.isdir(data_file) else \
            open(os.path.join(data_file, "words.txt")).read().splitlines()
        sents, cur = [], []
        for ln in lines:
            if ln.strip():
                cur.append(ln.split()[0])
            elif cur:
                sents.append(cur)
                cur = []
        if cur:
            sents.append(cur)
        freq = {}
        for s in sents:
            for w in s:
                freq[w] = freq.get(w, 0) + 1
        self.word_dict = {w: i for i, w in enumerate(
            sorted(freq, key=lambda w: (-freq[w], w)))}
        split = int(len(sents) * 0.8)
        sents = sents[:split] if mode == "train" else sents[split:]
        self.data = [np.array([self.word_dict[w] for w in s], np.int64)
                     for s in sents]

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)
