"""Text domain API (reference: python/paddle/text/__init__.py).

Datasets + Viterbi CRF decoding.  The reference datasets auto-download from
paddle's dataset mirror; this build runs zero-egress, so every dataset takes
an explicit local ``data_file``/``data_dir`` and parses the same archive
format the reference downloads (see each class).  ``viterbi_decode`` is a
lax.scan forward/backtrace pair — static shapes, jit-safe, TPU-resident —
replacing the reference's ViterbiDecodeOp C++ kernel (viterbi_decode_op.h).
"""

from .datasets import (Conll05st, Imdb, Imikolov, Movielens,  # noqa: F401
                       UCIHousing, WMT14, WMT16)
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]
