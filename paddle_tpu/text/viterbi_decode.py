"""Viterbi CRF decoding (reference: python/paddle/text/viterbi_decode.py:23,
C++ kernel operators/viterbi_decode_op.h).

TPU-native: the forward max-product recursion and the backtrace are both
``lax.scan``s over the time axis with static shapes — no dynamic control
flow, so the whole decode jit-compiles and stays device-resident.  Ragged
``lengths`` are handled by masking: steps beyond a sequence's length carry
state through unchanged, and the stop-tag transition is injected at each
sequence's own final position via a one-hot mask.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype
from jax import lax

from ..core.tensor import Tensor, apply
from ..nn import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi_raw(pot, trans, lengths, include_bos_eos_tag):
    B, L, N = pot.shape
    lengths = lengths.astype(jnp.int32)
    pot = pot.astype(jnp.float32)
    trans = trans.astype(jnp.float32)

    if include_bos_eos_tag:
        # start tag = last row; stop tag = second-to-last column
        # (reference semantics: viterbi_decode.py:60 docstring)
        init = pot[:, 0, :] + trans[-1, :][None, :]
        stop_at_end = (jnp.arange(L)[None, :] == (lengths - 1)[:, None])
        pot = pot + stop_at_end[:, :, None] * trans[:, -2][None, None, :]
        init = jnp.where((lengths == 1)[:, None],
                         pot[:, 0, :] + trans[-1, :][None, :], init)
    else:
        init = pot[:, 0, :]

    def fwd(carry, xs):
        alpha = carry                     # (B, N)
        pot_t, t = xs
        scores = alpha[:, :, None] + trans[None, :, :]    # (B, from, to)
        best = jnp.max(scores, axis=1) + pot_t            # (B, N)
        bp = jnp.argmax(scores, axis=1).astype(jnp.int32)
        active = (t < lengths)[:, None]
        return jnp.where(active, best, alpha), bp

    ts = jnp.arange(1, L)
    alpha, bps = lax.scan(fwd, init, (jnp.swapaxes(pot[:, 1:, :], 0, 1), ts))
    # bps: (L-1, B, N)

    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)   # (B,)

    def back(carry, xs):
        tag = carry                      # (B,)
        bp_t, t = xs                     # bp_t: (B, N); t = time of bp step
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        # only steps strictly inside the sequence update the running tag
        inside = t < lengths
        new_tag = jnp.where(inside, prev, tag)
        return new_tag, new_tag

    rev_ts = ts[::-1]
    _, rev_tags = lax.scan(back, last_tag, (bps[::-1], rev_ts))
    # rev_tags[k] is the tag at position rev_ts[k]-1; assemble full path
    tags_01 = jnp.concatenate([rev_tags[::-1].T, last_tag[:, None]], axis=1)
    # position t's tag: for t == length-1 it's last_tag only if length == L;
    # in general position t carries the tag chosen when scanning — mask below.
    pos = jnp.arange(L)[None, :]
    path = jnp.where(pos < lengths[:, None], tags_01, 0)
    return scores, path.astype(convert_dtype("int64"))


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag sequence per batch row.

    potentials: (B, L, N) emissions; transition_params: (N, N);
    lengths: (B,) int.  Returns (scores (B,), paths (B, max_len)).
    """
    out = apply(
        lambda p, t, ln: _viterbi_raw(p, t, ln, include_bos_eos_tag),
        potentials, transition_params, lengths)
    scores, path = out
    # eager parity with the reference: trim the path to the batch's max length
    pdata = path._data if isinstance(path, Tensor) else path
    if not isinstance(pdata, jax.core.Tracer):
        ln = getattr(lengths, "_data", lengths)
        if not isinstance(ln, jax.core.Tracer):
            maxlen = int(jnp.max(ln))
            path = Tensor(pdata[:, :maxlen])
    return scores, path


class ViterbiDecoder(Layer):
    """Layer wrapper (reference viterbi_decode.py:87)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
