"""Sparse tensors (reference: python/paddle's sparse_coo/sparse_csr kernel
family under paddle/phi/kernels/sparse in later snapshots).

TPU-native design: COO storage is ``jax.experimental.sparse.BCOO`` — XLA's
batched-COO format whose matmuls lower to gather/segment-sum HLO the TPU
executes natively, instead of hand-written CUDA scatter kernels.  A thin
``SparseCooTensor`` wrapper gives the paddle calling convention
(indices (ndim, nnz) int64, values (nnz,)), and CSR input is converted on
construction (the row-pointer form adds nothing on TPU where the matmul is
a dense-indexed gather anyway).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from .core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_sparse", "add", "subtract", "multiply", "matmul", "masked_matmul",
           "relu", "sin", "tanh", "sqrt", "coalesce"]


class SparseCooTensor:
    """COO sparse tensor: paddle layout (indices (ndim, nnz), values (nnz, ...))."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_parts(indices, values, shape):
        indices = jnp.asarray(getattr(indices, "_data", indices))
        values = jnp.asarray(getattr(values, "_data", values))
        if indices.ndim != 2:
            raise ValueError(f"indices must be (ndim, nnz), got {indices.shape}")
        bcoo = jsparse.BCOO((values, indices.T.astype(jnp.int32)),
                            shape=tuple(int(s) for s in shape))
        return SparseCooTensor(bcoo)

    # -- paddle surface ----------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(self._bcoo.indices.T)

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def transpose(self, perm):
        return SparseCooTensor(self._bcoo.transpose(tuple(perm)))

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    # -- arithmetic --------------------------------------------------------
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)

    def _map_values(self, fn):
        b = self._bcoo
        return SparseCooTensor(
            jsparse.BCOO((fn(b.data), b.indices), shape=b.shape))


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Build a COO sparse tensor from (ndim, nnz) indices + (nnz,) values."""
    ind = jnp.asarray(getattr(indices, "_data", indices))
    val = jnp.asarray(getattr(values, "_data", values))
    if dtype is not None:
        from .core.dtype import convert_dtype
        val = val.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(ind.max(axis=1)))
    return SparseCooTensor.from_parts(ind, val, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """Build from CSR (crows (nrow+1,), cols (nnz,), values (nnz,)) — stored COO."""
    crows = np.asarray(getattr(crows, "_data", crows))
    cols = jnp.asarray(getattr(cols, "_data", cols))
    values = jnp.asarray(getattr(values, "_data", values))
    counts = np.diff(crows)
    rows = jnp.asarray(np.repeat(np.arange(len(counts)), counts))
    ind = jnp.stack([rows, cols])
    return sparse_coo_tensor(ind, values, shape, dtype=dtype)


def is_sparse(x):
    return isinstance(x, SparseCooTensor)


def _sparse_linear_combine(a, b, beta):
    """a + beta*b for two COO operands without densifying: concatenate the
    index/value lists and merge duplicates with a static nse bound (jit-safe;
    memory stays O(nnz_a + nnz_b))."""
    ab, bb = a._bcoo, b._bcoo
    if ab.shape != bb.shape:
        raise ValueError(f"shape mismatch {ab.shape} vs {bb.shape}")
    vals = jnp.concatenate([ab.data, beta * bb.data.astype(ab.data.dtype)])
    idx = jnp.concatenate([ab.indices, bb.indices], axis=0)
    merged = jsparse.BCOO((vals, idx), shape=ab.shape)
    return SparseCooTensor(merged.sum_duplicates(nse=ab.nse + bb.nse))


def _binary(a, b, fn):
    # mixed sparse/dense: result is dense (reference convention)
    av = a._bcoo.todense() if is_sparse(a) else getattr(a, "_data", a)
    bv = b._bcoo.todense() if is_sparse(b) else getattr(b, "_data", b)
    return Tensor(fn(av, bv))


def add(x, y, name=None):
    if is_sparse(x) and is_sparse(y):
        return _sparse_linear_combine(x, y, 1.0)
    return _binary(x, y, jnp.add)


def subtract(x, y, name=None):
    if is_sparse(x) and is_sparse(y):
        return _sparse_linear_combine(x, y, -1.0)
    return _binary(x, y, jnp.subtract)


def multiply(x, y, name=None):
    """Elementwise multiply.  sparse × scalar stays sparse (value map);
    sparse × sparse / sparse × dense densify — the intersection pattern of
    two COO operands is data-dependent, which static shapes can't carry."""
    if is_sparse(x) and not is_sparse(y) and jnp.ndim(getattr(y, "_data", y)) == 0:
        return x._map_values(lambda v: v * jnp.asarray(getattr(y, "_data", y)))
    return _binary(x, y, jnp.multiply)


def matmul(x, y, name=None):
    """sparse @ dense (or dense @ sparse) → dense Tensor."""
    if is_sparse(x):
        yv = y._bcoo.todense() if is_sparse(y) else getattr(y, "_data", y)
        return Tensor(x._bcoo @ jnp.asarray(yv))
    if is_sparse(y):
        return Tensor(jnp.asarray(getattr(x, "_data", x)) @ y._bcoo)
    return Tensor(jnp.matmul(getattr(x, "_data", x), getattr(y, "_data", y)))


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) sampled at ``mask``'s sparsity pattern (SDDMM)."""
    xv = jnp.asarray(getattr(x, "_data", x))
    yv = jnp.asarray(getattr(y, "_data", y))
    idx = mask._bcoo.indices                       # (nnz, 2)
    rows, cols = idx[:, 0], idx[:, 1]
    vals = jnp.sum(xv[rows, :] * yv[:, cols].T, axis=-1)
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


def _unary(name, fn):
    def wrapper(x, name=None):
        if is_sparse(x):
            return x._map_values(fn)
        return Tensor(fn(getattr(x, "_data", x)))
    wrapper.__name__ = name
    return wrapper


relu = _unary("relu", lambda v: jnp.maximum(v, 0))
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)


def coalesce(x, name=None):
    return x.coalesce()
