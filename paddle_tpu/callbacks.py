"""Training callbacks (reference: python/paddle/callbacks.py — a re-export
of the hapi callback classes, mirrored here the same way).
``TelemetryCallback`` and ``GoodputCallback`` are paddle_tpu-specific: the
first wires a ``telemetry.TrainMonitor`` through ``Model.fit``, the second
a ``telemetry_ledger.RunLedger`` goodput attribution
(docs/OBSERVABILITY.md)."""

from .hapi.callbacks import (Callback, CallbackList, EarlyStopping,  # noqa: F401
                             GoodputCallback, LRScheduler, ManagedCheckpoint,
                             ModelCheckpoint, ProgBarLogger,
                             ReduceLROnPlateau, TelemetryCallback, VisualDL)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint",
           "ManagedCheckpoint", "LRScheduler", "EarlyStopping", "VisualDL",
           "ReduceLROnPlateau", "TelemetryCallback", "GoodputCallback"]
