"""Training callbacks (reference: python/paddle/callbacks.py — a re-export
of the hapi callback classes, mirrored here the same way).
``TelemetryCallback`` is paddle_tpu-specific: it wires a
``telemetry.TrainMonitor`` through ``Model.fit`` (docs/OBSERVABILITY.md)."""

from .hapi.callbacks import (Callback, CallbackList, EarlyStopping,  # noqa: F401
                             LRScheduler, ModelCheckpoint, ProgBarLogger,
                             ReduceLROnPlateau, TelemetryCallback, VisualDL)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL", "ReduceLROnPlateau",
           "TelemetryCallback"]
