"""Training callbacks (reference: python/paddle/callbacks.py — a re-export
of the hapi callback classes, mirrored here the same way)."""

from .hapi.callbacks import (Callback, CallbackList, EarlyStopping,  # noqa: F401
                             LRScheduler, ModelCheckpoint, ProgBarLogger,
                             ReduceLROnPlateau, VisualDL)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL", "ReduceLROnPlateau"]
