"""``paddle.static.nn`` — parameter-creating layer functions + control flow
(reference: python/paddle/static/nn/__init__.py; common.py fc:15, the
fluid.layers conv/norm family, and lax-native control flow instead of
conditional_block_op/while_op sub-block execution).

Sequence_* LoD ops are a declared non-goal (SURVEY §7 — ragged/segment ops
replace LoD); everything else on the reference's dense list is here.  The
"static" flavor means the function CREATES its parameters (reference
behavior under a program guard); under jit tracing the created parameters
become constants of the traced program unless bound through a Layer.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


def _raw(x):
    return getattr(x, "_data", x)


def _param(shape, dtype="float32", is_bias=False, attr=None):
    from . import create_parameter
    return create_parameter(list(shape), dtype, attr=attr, is_bias=is_bias)


def _tw(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


# ----------------------------------------------------------------- dense
def _activate(out, name):
    """Apply a named activation or raise — silent passthrough would drop a
    ported model's nonlinearity."""
    if name is None:
        return out
    import paddle_tpu.nn.functional as F
    fns = {"relu": F.relu, "softmax": F.softmax, "tanh": F.tanh,
           "sigmoid": F.sigmoid, "gelu": F.gelu, "leaky_relu": F.leaky_relu}
    if name not in fns:
        raise ValueError(f"unsupported activation {name!r}; "
                         f"one of {sorted(fns)}")
    return fns[name](out)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    import paddle_tpu.nn.functional as F
    xv = _raw(x)
    flat = xv.reshape(xv.shape[:num_flatten_dims] + (-1,))
    w = _param([flat.shape[-1], size], str(flat.dtype), attr=weight_attr)
    b = _param([size], str(flat.dtype), is_bias=True, attr=bias_attr)
    out = Tensor(flat) @ w + b
    return _activate(out, activation)


def embedding(input, size, is_sparse=False, padding_idx=None, dtype="float32",
              param_attr=None):
    import paddle_tpu.nn.functional as F
    w = _param(size, dtype, attr=param_attr)
    return F.embedding(_tw(input), w, padding_idx=padding_idx)


# dense fallback: sparse PS tables are a non-goal; the dense embedding has
# identical math (reference sparse_embedding is a storage-side optimization)
sparse_embedding = embedding


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    xv, yv = _raw(x), _raw(y)
    w = _param([size, xv.shape[-1], yv.shape[-1]], str(xv.dtype),
               attr=param_attr)
    b = _param([size], str(xv.dtype), is_bias=True, attr=bias_attr)
    out = jnp.einsum("bi,kij,bj->bk", xv, _raw(w), yv) + _raw(b)
    if act == "tanh":
        out = jnp.tanh(out)
    elif act == "relu":
        out = jax.nn.relu(out)
    return Tensor(out)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    import paddle_tpu.nn.functional as F
    xv = _raw(x)
    if mode == "element":
        # per-element alpha broadcasts directly (reference shape (1, *rest))
        alpha = _param((1,) + tuple(xv.shape[1:]), str(xv.dtype),
                       attr=param_attr)
        av = _raw(alpha)
        out = jnp.where(xv > 0, xv, xv * av)
        return Tensor(out)
    n = 1 if mode == "all" else xv.shape[1 if data_format[1] == "C" else -1]
    alpha = _param([n], str(xv.dtype), attr=param_attr)
    return F.prelu(_tw(x), alpha, data_format=data_format)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (≙ row_conv_op): out[t] = Σ_{i=0..k}
    w[i] ⊙ x[t+i] over a per-channel weight window into the future."""
    xv = _raw(input)  # (B, T, D)
    k = int(future_context_size)
    w = _param([k + 1, xv.shape[-1]], str(xv.dtype), attr=param_attr)
    wv = _raw(w)
    pad = jnp.pad(xv, ((0, 0), (0, k), (0, 0)))
    out = sum(pad[:, i:i + xv.shape[1], :] * wv[i] for i in range(k + 1))
    if act == "tanh":
        out = jnp.tanh(out)
    return Tensor(out)


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=10, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (≙ nce_op): logistic regression of
    the true class against ``num_neg_samples`` uniformly drawn noise classes.
    Returns per-example loss (B, 1)."""
    xv = _raw(input)                      # (B, D)
    lv = _raw(label).reshape(-1)          # (B,)
    B, D = xv.shape
    w = _param([num_total_classes, D], str(xv.dtype), attr=param_attr)
    b = _param([num_total_classes], str(xv.dtype), is_bias=True,
               attr=bias_attr)
    wv, bv = _raw(w), _raw(b)
    neg = jax.random.randint(jax.random.key(seed), (B, num_neg_samples), 0,
                             num_total_classes)
    pos_logit = jnp.sum(xv * wv[lv], -1) + bv[lv]                    # (B,)
    neg_logit = jnp.einsum("bd,bnd->bn", xv, wv[neg]) + bv[neg]      # (B, n)
    logsig = jax.nn.log_sigmoid
    loss = -(logsig(pos_logit) + jnp.sum(logsig(-neg_logit), -1))
    return Tensor(loss[:, None])


# ----------------------------------------------------------------- convs
def _conv_nd(fn, input, num_filters, filter_size, stride, padding, dilation,
             groups, param_attr, bias_attr, data_format, ndim, transpose=False,
             output_size=None):
    xv = _raw(input)
    cin = xv.shape[1 if data_format[1] == "C" else -1]
    fs = (filter_size,) * ndim if isinstance(filter_size, int) \
        else tuple(filter_size)
    if transpose:
        wshape = (cin, num_filters // (groups or 1)) + fs
    else:
        wshape = (num_filters, cin // (groups or 1)) + fs
    w = _param(wshape, str(xv.dtype), attr=param_attr)
    b = None if bias_attr is False else _param([num_filters], str(xv.dtype),
                                               is_bias=True, attr=bias_attr)
    kw = {"output_size": output_size} if transpose and output_size is not None \
        else {}
    return fn(_tw(input), w, b, stride=stride, padding=padding,
              dilation=dilation, groups=groups or 1, data_format=data_format,
              **kw)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    import paddle_tpu.nn.functional as F
    out = _conv_nd(F.conv2d, input, num_filters, filter_size, stride, padding,
                   dilation, groups, param_attr, bias_attr, data_format, 2)
    return _activate(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    import paddle_tpu.nn.functional as F
    out = _conv_nd(F.conv3d, input, num_filters, filter_size, stride, padding,
                   dilation, groups, param_attr, bias_attr, data_format, 3)
    return _activate(out, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True, act=None,
                     name=None, data_format="NCHW"):
    import paddle_tpu.nn.functional as F
    out = _conv_nd(F.conv2d_transpose, input, num_filters, filter_size, stride,
                   padding, dilation, groups, param_attr, bias_attr,
                   data_format, 2, transpose=True, output_size=output_size)
    return _activate(out, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True, act=None,
                     name=None, data_format="NCDHW"):
    import paddle_tpu.nn.functional as F
    out = _conv_nd(F.conv3d_transpose, input, num_filters, filter_size, stride,
                   padding, dilation, groups, param_attr, bias_attr,
                   data_format, 3, transpose=True, output_size=output_size)
    return _activate(out, act)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    from ..vision.ops import deform_conv2d as _dc
    xv = _raw(x)
    fs = (filter_size,) * 2 if isinstance(filter_size, int) else tuple(filter_size)
    w = _param((num_filters, xv.shape[1] // groups) + fs, str(xv.dtype),
               attr=param_attr)
    b = None if bias_attr is False else _param([num_filters], str(xv.dtype),
                                               is_bias=True, attr=bias_attr)
    return _dc(_tw(x), _tw(offset), w, b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=None if mask is None else _tw(mask))


# ----------------------------------------------------------------- norms
def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW", **kwargs):
    from ..nn import BatchNorm1D, BatchNorm2D, BatchNorm3D
    xv = _raw(input)
    cls = {2: BatchNorm1D, 3: BatchNorm1D, 4: BatchNorm2D, 5: BatchNorm3D}[xv.ndim]
    bn = cls(xv.shape[1], momentum=momentum, epsilon=epsilon)
    if is_test:
        bn.eval()
    out = bn(_tw(input))
    return _activate(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    import paddle_tpu.nn.functional as F
    xv = _raw(input)
    shape = xv.shape[begin_norm_axis:]
    w = _param(shape, str(xv.dtype), attr=param_attr) if scale else None
    b = _param(shape, str(xv.dtype), is_bias=True, attr=bias_attr) if shift else None
    return F.layer_norm(_tw(input), shape, weight=w, bias=b, epsilon=epsilon)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    import paddle_tpu.nn.functional as F
    xv = _raw(input)
    C = xv.shape[1]
    w = _param([C], str(xv.dtype), attr=param_attr)
    b = _param([C], str(xv.dtype), is_bias=True, attr=bias_attr)
    return F.instance_norm(_tw(input), weight=w, bias=b, eps=epsilon)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    import paddle_tpu.nn.functional as F
    xv = _raw(input)
    C = xv.shape[1 if data_layout[1] == "C" else -1]
    w = _param([C], str(xv.dtype), attr=param_attr)
    b = _param([C], str(xv.dtype), is_bias=True, attr=bias_attr)
    out = F.group_norm(_tw(input), groups, weight=w, bias=b, epsilon=epsilon,
                       data_format=data_layout)
    return _activate(out, act)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, data_layout=None,
              in_place=False, name=None, moving_mean_name=None,
              moving_variance_name=None, do_model_average_for_mean_and_var=True,
              slot_dim=-1, summary_decay_rate=0.9999999, sync_stats=False,
              enable_scale_and_shift=False):
    """Feature-wise normalization from accumulated batch statistics
    (≙ data_norm_op — CTR-style scale-free normalization)."""
    xv = _raw(input).astype(jnp.float32)
    mean = jnp.mean(xv, axis=0, keepdims=True)
    var = jnp.var(xv, axis=0, keepdims=True)
    out = (xv - mean) * jax.lax.rsqrt(var + epsilon)
    if enable_scale_and_shift:
        w = _param([xv.shape[-1]], "float32", attr=param_attr)
        b = _param([xv.shape[-1]], "float32", is_bias=True)
        out = out * _raw(w) + _raw(b)
    return Tensor(out)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectral normalization of a weight tensor (≙ spectral_norm_op)."""
    wv = _raw(weight)
    wmat = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
    u = jnp.ones((wmat.shape[0],), wmat.dtype)
    for _ in range(max(1, power_iters)):
        v = wmat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wmat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ wmat @ v
    return Tensor(wv / (sigma + eps))


# ----------------------------------------------------------- control flow
def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """``lax.cond`` (≙ conditional_block_op sub-block execution).  Either
    branch fn may be None (reference contract) — a None branch is a no-op
    returning None, which requires the other branch to return None too."""
    t_fn = true_fn if true_fn is not None else (lambda: None)
    f_fn = false_fn if false_fn is not None else (lambda: None)
    p = _raw(pred)
    # evaluate each branch exactly ONCE (lax.cond traces both branches
    # anyway; re-calling the fns would double side effects like parameter
    # creation), then select between the pre-evaluated pytrees
    t_out = jax.tree_util.tree_map(_raw, t_fn())
    f_out = jax.tree_util.tree_map(_raw, f_fn())
    t_struct = jax.tree_util.tree_structure(t_out)
    f_struct = jax.tree_util.tree_structure(f_out)
    if t_struct != f_struct:
        raise ValueError(
            f"cond branches must return the same structure, got {t_struct} "
            f"vs {f_struct} (a None branch returns None)")
    if t_struct == jax.tree_util.tree_structure(None):
        return None  # both branches are no-ops
    out = jax.lax.cond(jnp.reshape(p, ()).astype(bool),
                       lambda ops: ops[0], lambda ops: ops[1],
                       (t_out, f_out))
    return jax.tree_util.tree_map(Tensor, out)


def case(pred_fn_pairs, default=None, name=None):
    """First-true-wins chain of (pred, fn) (≙ case in control_flow.py)."""
    if default is None:
        *pred_fn_pairs, last = pred_fn_pairs
        default = last[1]
    result = default
    for pred, fn in reversed(list(pred_fn_pairs)):
        prev = result
        result = (lambda pr, f, pv: lambda: cond(pr, f, pv))(pred, fn, prev)
    return result() if callable(result) else result


def switch_case(branch_index, branch_fns, default=None, name=None):
    """``lax.switch`` (≙ switch_case in control_flow.py)."""
    if (isinstance(branch_fns, (list, tuple)) and branch_fns
            and all(isinstance(b, (list, tuple)) and len(b) == 2
                    for b in branch_fns)):
        # reference also canonicalizes [(index, fn), ...] (control_flow.py:3688)
        branch_fns = dict(branch_fns)
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        # map branch_index → dense position
        idx = _raw(branch_index)
        dense = sum(jnp.where(idx == k, i, 0) for i, k in enumerate(keys))
        hit = sum((idx == k).astype(jnp.int32) for k in keys)
        if default is not None:
            fns = fns + [default]
            dense = jnp.where(hit > 0, dense, len(keys))
        else:  # reference: unmatched index falls back to the LARGEST key
            dense = jnp.where(hit > 0, dense, len(keys) - 1)
    else:
        fns = list(branch_fns)
        idx = _raw(branch_index)
        in_range = (idx >= 0) & (idx < len(fns))
        if default is not None:
            fns = fns + [default]
            dense = jnp.where(in_range, jnp.clip(idx, 0, len(fns) - 2),
                              len(fns) - 1)
        else:  # reference: out-of-range runs the LAST branch
            dense = jnp.where(in_range, jnp.clip(idx, 0, len(fns) - 1),
                              len(fns) - 1)
    out = jax.lax.switch(jnp.reshape(dense, ()),
                         [lambda _, f=f: jax.tree_util.tree_map(_raw, f())
                          for f in fns], None)
    return jax.tree_util.tree_map(Tensor, out)


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """``lax.while_loop`` (≙ while_op sub-block execution)."""
    raw_vars = jax.tree_util.tree_map(_raw, loop_vars)

    def c(vs):
        return jnp.reshape(_raw(cond(*jax.tree_util.tree_map(Tensor, vs))),
                           ()).astype(bool)

    def b(vs):
        return jax.tree_util.tree_map(
            _raw, body(*jax.tree_util.tree_map(Tensor, vs)))

    out = jax.lax.while_loop(c, b, raw_vars)
    return jax.tree_util.tree_map(Tensor, out)


# ---------------------------------------------------------------- decode
def crf_decoding(input, param_attr=None, label=None, length=None,
                 transition=None):
    """Viterbi decode over emission scores (≙ crf_decoding_op); transition
    defaults to a created parameter like the reference's CRF weight."""
    from ..text import viterbi_decode
    xv = _raw(input)
    n_tags = xv.shape[-1]
    if transition is None:
        transition = _param([n_tags + 2, n_tags], str(xv.dtype),
                            attr=param_attr)
    tv = _raw(transition)
    # reference layout carries start/stop rows first; after stripping them
    # the matrix holds ordinary transitions only, so the decoder must not
    # reinterpret rows as BOS/EOS bonuses
    has_bos_eos = tv.shape[0] != n_tags
    trans = tv[-n_tags:] if has_bos_eos else tv
    if xv.ndim == 2:
        xv = xv[None]
    lens = _raw(length) if length is not None else \
        jnp.full((xv.shape[0],), xv.shape[1], jnp.int32)
    scores, path = viterbi_decode(Tensor(xv), Tensor(trans),
                                  Tensor(jnp.asarray(lens)),
                                  include_bos_eos_tag=False)
    if label is not None:
        # reference: with a gold label the op returns per-position 0/1
        # correctness, not the path
        lv = _raw(label)
        if lv.ndim == path._data.ndim + 1 and lv.shape[-1] == 1:
            lv = lv[..., 0]
        return Tensor((_raw(path) == lv).astype(jnp.int32))
    return path


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, offset=0.5, flip=True,
                   kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (≙ multi_box_head in detection.py): per feature
    map, prior boxes + conv loc/conf predictions, concatenated."""
    import paddle_tpu.nn.functional as F
    n_in = len(inputs)
    if min_sizes is None:
        # reference ratio schedule: evenly spaced between min/max ratio
        min_ratio, max_ratio = float(min_ratio), float(max_ratio)
        step = (max_ratio - min_ratio) / max(n_in - 2, 1)
        min_sizes, max_sizes = [base_size * 0.1], [base_size * 0.2]
        r = min_ratio
        for _ in range(n_in - 1):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
            r += step
    locs, confs, boxes_all, vars_all = [], [], [], []
    img_h, img_w = _raw(image).shape[2:]
    for i, feat in enumerate(inputs):
        fv = _raw(feat)
        N, C, H, W = fv.shape
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        # priors per cell: 1 (min) + 1 (sqrt(min*max)) + len(ar)*(2 if flip)
        mn = float(min_sizes[i])
        mx = float(max_sizes[i]) if max_sizes is not None else None
        sizes = [(mn, mn)]
        if mx is not None:  # the sqrt(min*max) prior needs a max size
            sizes.append((np.sqrt(mn * mx), np.sqrt(mn * mx)))
        for a in ar:
            sizes.append((mn * np.sqrt(a), mn / np.sqrt(a)))
            if flip:
                sizes.append((mn / np.sqrt(a), mn * np.sqrt(a)))
        n_prior = len(sizes)
        step_w = steps[i] if steps else img_w / W
        step_h = steps[i] if steps else img_h / H
        cx = (jnp.arange(W) + offset) * step_w / img_w
        cy = (jnp.arange(H) + offset) * step_h / img_h
        cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), -1)  # (H, W, 2)
        pb = []
        for (sw, sh) in sizes:
            half = jnp.asarray([sh / img_h / 2, sw / img_w / 2])
            mins = cyx - half
            maxs = cyx + half
            pb.append(jnp.concatenate([mins[..., ::-1], maxs[..., ::-1]], -1))
        prior = jnp.clip(jnp.stack(pb, 2).reshape(H * W * len(sizes), 4), 0, 1)
        boxes_all.append(prior)
        vars_all.append(jnp.broadcast_to(jnp.asarray([0.1, 0.1, 0.2, 0.2]),
                                         prior.shape))
        loc = conv2d(feat, n_prior * 4, kernel_size, stride=stride, padding=pad)
        conf = conv2d(feat, n_prior * num_classes, kernel_size, stride=stride,
                      padding=pad)
        locs.append(_raw(loc).transpose(0, 2, 3, 1).reshape(N, -1, 4))
        confs.append(_raw(conf).transpose(0, 2, 3, 1).reshape(N, -1, num_classes))
    return (Tensor(jnp.concatenate(locs, 1)),
            Tensor(jnp.concatenate(confs, 1)),
            Tensor(jnp.concatenate(boxes_all, 0)),
            Tensor(jnp.concatenate(vars_all, 0)))


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    from . import py_func as _pf
    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


__all__ = [
    "fc", "batch_norm", "embedding", "bilinear_tensor_product", "case",
    "cond", "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "crf_decoding", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "multi_box_head", "nce", "prelu",
    "py_func", "row_conv", "spectral_norm", "switch_case", "while_loop",
    "sparse_embedding",
]
