"""Static-graph compatibility facade (reference: python/paddle/static/__init__.py).

SURVEY §7 design: the reference's Program/Executor/ParallelExecutor stack
collapses into jax tracing + XLA — a "static program" here IS a traced
``StaticFunction`` (jit/__init__.py), and the Executor runs it.  This module
keeps the reference's calling convention alive for code written against
``paddle.static``:

- ``InputSpec`` / ``data``          → symbolic input declarations (jit.InputSpec)
- ``Program`` / ``program_guard``   → lightweight namespaces (random seed,
  collected parameters); graph capture happens at trace time, not op-record time
- ``Executor.run``                  → jit-compile + execute a traced callable
- ``save/load_inference_model``     → StableHLO export round-trip via jit.save/load
- ``ExponentialMovingAverage``      → real EMA with apply/restore context
- ``accuracy``/``auc``              → metric wrappers

Entry points that only make sense for a mutable op-by-op graph IR
(``append_backward``, ``py_func``) raise with a pointer to the dynamic API.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..jit import InputSpec, StaticFunction, to_static
from ..jit import load as _jit_load
from ..jit import save as _jit_save

__all__ = [
    "InputSpec", "data", "Program", "program_guard", "default_main_program",
    "default_startup_program", "Executor", "global_scope", "scope_guard",
    "name_scope", "device_guard", "cpu_places", "cuda_places",
    "save", "load", "save_inference_model", "load_inference_model",
    "ExponentialMovingAverage", "accuracy", "auc", "create_global_var",
    "create_parameter", "WeightNormParamAttr", "gradients", "append_backward",
    "BuildStrategy", "ExecutionStrategy", "CompiledProgram", "ParallelExecutor",
    "py_func", "Print", "nn",
]


def data(name: str, shape, dtype="float32", lod_level=0):
    """Declare a graph input (reference static/input.py data())."""
    return InputSpec(shape=shape, dtype=dtype, name=name)


class Program:
    """Placeholder program object: seed + parameter scope (compat surface).

    The actual computation graph is captured by tracing (to_static); this
    object carries the attributes user code reads/writes on
    ``default_main_program()``.
    """

    def __init__(self):
        self.random_seed = 0
        self._params: Dict[str, Tensor] = {}

    def global_block(self):
        return self

    def parameters(self):
        return list(self._params.values())

    def clone(self, for_test: bool = False):
        return self


_main_program = Program()
_startup_program = Program()
_program_stack: List[Program] = []
_static_mode = False


def _enable():
    global _static_mode
    _static_mode = True


def _disable():
    global _static_mode
    _static_mode = False


def _enabled() -> bool:
    return _static_mode


def default_main_program() -> Program:
    return _program_stack[-1] if _program_stack else _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _program_stack.append(main_program)
    try:
        yield
    finally:
        _program_stack.pop()


class _Scope:
    def __init__(self):
        self.vars: Dict[str, Any] = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        return self.vars.get(name)


_global_scope = _Scope()


def global_scope() -> _Scope:
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    yield scope


@contextlib.contextmanager
def name_scope(prefix: str):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


def cpu_places(device_count: Optional[int] = None):
    from ..core.device import Place, local_devices
    n = device_count or len(local_devices("cpu"))
    return [Place(f"cpu:{i}") for i in range(n)]


def cuda_places(device_ids=None):
    raise RuntimeError("No CUDA places in a TPU build; use tpu devices "
                      "(paddle.device.local_devices())")


class Executor:
    """Compile-and-run front door (reference static/Executor → here jit).

    ``run(program_or_fn, feed=..., fetch_list=...)``: when given a
    StaticFunction/callable it jit-executes it on the feed values; Program
    objects (the compat placeholders) just return the fetches from feed.
    """

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        feed = feed or {}
        if callable(program) or isinstance(program, StaticFunction):
            # match feed entries to the callable's parameters by NAME (the
            # reference Executor's contract); fall back to insertion order
            # only when the signature is unavailable or names don't line up
            import inspect
            vals = list(feed.values())
            target = program
            if isinstance(program, StaticFunction) and program._layer is not None:
                target = program._layer.forward
            try:
                names = [p.name for p in
                         inspect.signature(target).parameters.values()
                         if p.kind in (p.POSITIONAL_ONLY,
                                       p.POSITIONAL_OR_KEYWORD)]
                if set(feed) <= set(names):
                    vals = [feed[n] for n in names if n in feed]
            except (TypeError, ValueError, AttributeError):
                pass
            args = [jnp.asarray(getattr(v, "_data", v)) for v in vals]
            out = program(*args)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [np.asarray(getattr(o, "_data", o)) for o in outs]
        # Program placeholder: nothing to execute (tracing captured the graph)
        if fetch_list:
            return [np.asarray(getattr(f, "_data", f)) for f in fetch_list]
        return []

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Dataset trainer loop (reference fluid/executor.py:1629 — the
        Trainer/DeviceWorker entry).  ``program`` is the per-batch train
        callable ``step(*batch) -> loss`` (the jitted train step built by
        jit.make_train_step or any callable); ``dataset`` an
        io.InMemoryDataset/QueueDataset.  Parsing threads come from the
        dataset's ``set_thread``; compute is the single SPMD program.
        Returns the list of per-batch losses.
        """
        if dataset is None or program is None:
            raise ValueError("train_from_dataset needs program= and dataset=")
        if thread:
            dataset.set_thread(thread)
        device_losses = []
        for i, batch in enumerate(dataset):
            out = program(*batch)
            loss = out[0] if isinstance(out, (list, tuple)) else out
            # keep the DEVICE scalar: a per-batch float() would sync every
            # step and serialize host IO with device compute; only the
            # debug print (at print_period cadence) pays a sync
            device_losses.append(getattr(loss, "_data", loss))
            if debug and print_period and i % print_period == 0:
                print(f"[train_from_dataset] batch {i} loss "
                      f"{float(np.asarray(device_losses[-1])):.6f}")
        return [float(np.asarray(l)) for l in device_losses]

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Inference twin of train_from_dataset: collects program outputs."""
        if dataset is None or program is None:
            raise ValueError("infer_from_dataset needs program= and dataset=")
        outs = []
        for batch in dataset:
            out = program(*batch)
            first = out[0] if isinstance(out, (list, tuple)) else out
            outs.append(np.asarray(getattr(first, "_data", first)))
        return outs


def save(program, model_path: str, protocol=4):
    from ..framework import io as _io
    _io.save({n: p for n, p in getattr(program, "_params", {}).items()},
             model_path if model_path.endswith(".pdparams")
             else model_path + ".pdparams")


def load(program, model_path: str, executor=None, var_list=None):
    from ..framework import io as _io
    path = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    return _io.load(path)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """Export a traced layer/function (StableHLO) for inference serving."""
    layer = kwargs.get("program") or fetch_vars
    specs = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    return _jit_save(layer, path_prefix, input_spec=list(specs))


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    layer = _jit_load(path_prefix)
    return layer


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy of softmax ``input`` vs int ``label`` (static.accuracy)."""
    x = getattr(input, "_data", input)
    y = getattr(label, "_data", label).reshape(-1)
    topk = jnp.argsort(-x, axis=-1)[..., :k]
    hit = jnp.any(topk == y[:, None], axis=-1)
    return Tensor(jnp.mean(hit.astype(jnp.float32)))


def auc(input, label, curve="ROC", num_thresholds=4095, **kwargs):
    from ..metric import Auc
    m = Auc(num_thresholds=num_thresholds)
    m.update(np.asarray(getattr(input, "_data", input)),
             np.asarray(getattr(label, "_data", label)))
    return Tensor(jnp.asarray(m.accumulate(), jnp.float32))


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    from ..core.dtype import convert_dtype
    return Tensor(jnp.full(tuple(shape), value, convert_dtype(dtype)))


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.tensor import Parameter
    from ..nn.initializer import Constant, XavierNormal
    init = default_initializer or (Constant(0.0) if is_bias else XavierNormal())
    data = init(tuple(shape), dtype)
    p = Parameter(data, trainable=True)
    if name:
        default_main_program()._params[name] = p
    return p


class WeightNormParamAttr:
    def __init__(self, dim=None, **kwargs):
        self.dim = dim
        self.kwargs = kwargs


class ExponentialMovingAverage:
    """EMA of parameter values with apply/restore (static/ExponentialMovingAverage).

    ``update()`` after each optimizer step; ``apply()`` context swaps EMA
    values in for evaluation and restores on exit.
    """

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._ema: Dict[int, Any] = {}
        self._backup: Dict[int, Any] = {}
        self._params: List[Any] = []
        self._step = 0

    def _track(self, params):
        for p in params:
            if id(p) not in self._ema:
                self._params.append(p)
                self._ema[id(p)] = jnp.array(p._data)

    def update(self, parameters=None):
        if parameters is not None:
            self._track(parameters)
        self._step += 1
        # bias-corrected decay ramp, matching the reference's thres_steps form
        d = min(self._decay, (1.0 + self._step) / (10.0 + self._step))
        for p in self._params:
            self._ema[id(p)] = d * self._ema[id(p)] + (1.0 - d) * p._data

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            self._backup[id(p)] = p._data
            p._data = self._ema[id(p)]
        try:
            yield self
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup.pop(id(p))


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Eager-compatible gradients (reference static.gradients)."""
    from .. import grad as _grad
    return _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True)


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    raise RuntimeError(
        "append_backward operates on a mutable op-graph IR; in paddle_tpu the "
        "backward pass is derived by jax.grad at trace time — use "
        "paddle.grad / loss.backward() or the jit train-step builders.")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    raise RuntimeError(
        "py_func embeds host callbacks in the static graph; use an eager "
        "PyLayer (paddle.autograd.PyLayer) or jax.pure_callback instead.")


def Print(input, **kwargs):
    print(np.asarray(getattr(input, "_data", input)))
    return input


class BuildStrategy:
    """Accepted-and-ignored knobs (XLA owns fusion/placement decisions)."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_all_optimizer_ops = True
        self.memory_optimize = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, *a, **k):
        return self


ParallelExecutor = Executor


# ``paddle.static.nn`` namespace (static/nn.py — parameter-creating layer
# functions + lax-native control flow)
from . import nn  # noqa: E402,F401


# --------------------------------------------------------------------------
# Program serialization surface (reference: static/io.py — serialize_program
# :414, serialize_persistables :447, save_to_file :514, deserialize_program
# :584, deserialize_persistables :615, load_from_file :693, normalize_program
# :358; fluid/io.py load_program_state :2191, set_program_state :2305).
# The TPU program IR is the traced jaxpr/StableHLO (jit.save); what a static
# Program carries here is its parameter scope, so (de)serialization is over
# that state — the graph itself serializes through ``jit.save``.
# --------------------------------------------------------------------------

def serialize_program(feed_vars=None, fetch_vars=None, program=None, **kw):
    import pickle
    prog = program or default_main_program()
    meta = {"random_seed": getattr(prog, "random_seed", 0),
            "params": sorted(getattr(prog, "_params", {}))}
    return pickle.dumps(meta, protocol=4)


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None, **kw):
    import pickle
    prog = program or default_main_program()
    state = {n: np.asarray(getattr(p, "_data", p))
             for n, p in getattr(prog, "_params", {}).items()}
    return pickle.dumps(state, protocol=4)


def save_to_file(path: str, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data: bytes):
    import pickle
    meta = pickle.loads(data)
    prog = Program()
    prog.random_seed = meta.get("random_seed", 0)
    return prog


def deserialize_persistables(program, data: bytes, executor=None):
    import pickle
    state = pickle.loads(data)
    params = getattr(program, "_params", None)
    if params is not None:
        for n, v in state.items():
            params[n] = Tensor(jnp.asarray(v))
    return state


def normalize_program(program, feed_vars=None, fetch_vars=None):
    """Reference normalize_program prunes feed/fetch ops for inference export;
    traced jaxprs are already feed/fetch-free, so this is the identity."""
    return program


def load_program_state(model_path: str, var_list=None):
    from ..framework import io as _io
    path = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    state = _io.load(path)
    if var_list is not None:
        names = {getattr(v, "name", v) for v in var_list}
        state = {k: v for k, v in state.items() if k in names}
    return state


def set_program_state(program, state_dict):
    params = getattr(program, "_params", None)
    if params is not None:
        for n, v in state_dict.items():
            params[n] = v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))


def xpu_places(device_ids=None):
    raise RuntimeError("Not compiled with XPU — this build targets TPU "
                      "(reference static xpu_places has the same gate)")


def npu_places(device_ids=None):
    raise RuntimeError("Not compiled with NPU — this build targets TPU "
                      "(reference static npu_places has the same gate)")


Variable = Tensor  # static Variable ≙ traced Tensor (framework.py:915)

__all__ += [
    "serialize_program", "serialize_persistables", "save_to_file",
    "deserialize_program", "deserialize_persistables", "load_from_file",
    "normalize_program", "load_program_state", "set_program_state",
    "xpu_places", "npu_places", "Variable",
]
