"""tpulint rules — each grounded in a hazard this tree already exhibits.

Every rule documents the *consequence* (what breaks on TPU, silently),
because none of these fail a CPU unit test: trace-time impurity bakes
stale values into compiled programs, donated-buffer reuse aliases freed
device memory, unseeded randomness in ``distributed/`` desyncs replicas,
import-time device touches latch the platform before ``JAX_PLATFORMS``
config can land.  See docs/STATIC_ANALYSIS.md for the catalog.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import FileContext, Finding, Rule, register

JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
PARTIAL_NAMES = {"functools.partial", "partial"}


def _const_int_tuple(node: Optional[ast.AST]) -> Optional[Tuple[int, ...]]:
    """Literal int / tuple-of-ints value of an argnums expression, or None
    when it's computed (e.g. ``(0,) if donate else ()``) — computed argnums
    are opaque to the AST and deliberately not guessed at."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _const_str_tuple(node: Optional[ast.AST]) -> Optional[Tuple[str, ...]]:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, str)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


@dataclasses.dataclass
class JitSpec:
    """Parsed ``jax.jit`` wrapping: which params are static (not traced) and
    which argument positions are donated."""

    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    kwargs: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)


def _jit_call_spec(ctx: FileContext, call: ast.Call) -> Optional[JitSpec]:
    """JitSpec for ``jax.jit(f, ...)`` / ``functools.partial(jax.jit, ...)``
    call nodes; None when the call isn't a jit wrapping."""
    name = ctx.resolve(call.func)
    if name in PARTIAL_NAMES or (name or "").endswith(".partial"):
        if not (call.args and ctx.resolve(call.args[0]) in JIT_NAMES):
            return None
    elif name not in JIT_NAMES:
        return None
    kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
    return JitSpec(
        static_argnums=_const_int_tuple(kwargs.get("static_argnums")) or (),
        static_argnames=_const_str_tuple(kwargs.get("static_argnames")) or (),
        donate_argnums=_const_int_tuple(kwargs.get("donate_argnums")) or (),
        kwargs=kwargs)


def _jit_decorator_spec(ctx: FileContext, fn: ast.FunctionDef) -> Optional[JitSpec]:
    """JitSpec when ``fn`` is decorated ``@jax.jit`` or
    ``@functools.partial(jax.jit, ...)``; None otherwise."""
    for dec in fn.decorator_list:
        if ctx.resolve(dec) in JIT_NAMES:
            return JitSpec()
        if isinstance(dec, ast.Call):
            spec = _jit_call_spec(ctx, dec)
            if spec is not None:
                return spec
    return None


def _param_names(fn: ast.FunctionDef) -> List[str]:
    args = fn.args
    return [a.arg for a in args.posonlyargs + args.args]


def _static_params(fn: ast.FunctionDef, spec: JitSpec) -> Set[str]:
    params = _param_names(fn)
    static = set(spec.static_argnames)
    for i in spec.static_argnums:
        if 0 <= i < len(params):
            static.add(params[i])
    return static


def _walk_skipping_nested_defs(body: Sequence[ast.stmt]) -> Iterable[ast.AST]:
    """Walk statements without descending into nested def/class/lambda
    BODIES — nested functions run only if called, and flagging their bodies
    against the *outer* scope produces noise, not signal.  The scope nodes
    themselves ARE yielded (rules flag e.g. a @jit def in a loop), and so
    are the parts that DO execute with the enclosing statement: decorators,
    default values/annotations, class bases (the pop-time guard; the old
    child-only guard walked straight into defs that were direct statements
    of the walked body)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(node.decorator_list)
            stack.extend(ast.iter_child_nodes(node.args))
        elif isinstance(node, ast.Lambda):
            stack.extend(ast.iter_child_nodes(node.args))
        elif isinstance(node, ast.ClassDef):
            stack.extend(node.decorator_list)
            stack.extend(node.bases)
        else:
            stack.extend(ast.iter_child_nodes(node))


def _jitted_functions(ctx: FileContext) -> List[Tuple[ast.FunctionDef, JitSpec]]:
    # cached on the context: three rules ask for this list per file
    cached = getattr(ctx, "_jit_fns", None)
    if cached is None:
        cached = [(node, spec) for node in ast.walk(ctx.tree)
                  if isinstance(node, ast.FunctionDef)
                  and (spec := _jit_decorator_spec(ctx, node)) is not None]
        ctx._jit_fns = cached
    return cached


# ------------------------------------------------------------------- rule 1

#: call fullnames whose value is frozen at trace time — the compiled program
#: replays the value captured during tracing, forever
IMPURE_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "os.getenv", "os.environ.get",
    "print",
}
IMPURE_PREFIXES = ("random.", "numpy.random.")


@register
class HostImpurityInJit(Rule):
    name = "host-impurity-in-jit"
    hints = ("jit",)
    hazard = ("host state read inside @jax.jit is evaluated once at trace "
              "time and baked into the compiled program — every later call "
              "replays the stale value")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for fn, _spec in _jitted_functions(ctx):
            for node in _walk_skipping_nested_defs(fn.body):
                if isinstance(node, ast.Call):
                    name = ctx.resolve(node.func)
                    if name and (name in IMPURE_CALLS
                                 or name.startswith(IMPURE_PREFIXES)):
                        yield self.finding(
                            ctx, node,
                            f"{name}() inside jitted {fn.name}() runs at "
                            f"trace time only — its value is baked into the "
                            f"compiled program")
                elif isinstance(node, ast.Subscript):
                    if ctx.resolve(node.value) == "os.environ":
                        yield self.finding(
                            ctx, node,
                            f"os.environ read inside jitted {fn.name}() is "
                            f"latched at trace time — late env changes are "
                            f"invisible")


# ------------------------------------------------------------------- rule 2

@register
class DonatedArgReuse(Rule):
    name = "donated-arg-reuse"
    hints = ("donate_argnums",)
    hazard = ("an argument donated to a jitted call aliases freed device "
              "memory afterwards — reading it returns garbage or raises, "
              "depending on backend and timing")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        # Per-scope linear scan: collect names bound to jit wrappings with
        # literal donate_argnums, then after each call through one, any Load
        # of a donated argument name — until it is rebound — is a use of a
        # donated buffer.
        scopes: List[Sequence[ast.stmt]] = [ctx.tree.body]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        for body in scopes:
            yield from self._scan_scope(ctx, body)

    def _scan_scope(self, ctx: FileContext, body: Sequence[ast.stmt]):
        donors: Dict[str, Tuple[int, ...]] = {}
        for fn_node in (n for n in body if isinstance(n, ast.FunctionDef)):
            spec = _jit_decorator_spec(ctx, fn_node)
            if spec is not None and spec.donate_argnums:
                donors[fn_node.name] = spec.donate_argnums
        dead: Dict[str, Tuple[str, int]] = {}  # name -> (callee, call line)
        for stmt in body:
            # uses before (re)binding within this statement
            for node in ast.walk(stmt):
                if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                        and node.id in dead):
                    callee, line = dead[node.id]
                    yield self.finding(
                        ctx, node,
                        f"{node.id!r} was donated to {callee}() on line "
                        f"{line}; its buffer may already be freed/aliased")
            # new donors bound in this scope: g = jax.jit(f, donate_argnums=..)
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                spec = _jit_call_spec(ctx, stmt.value)
                if spec is not None and spec.donate_argnums:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            donors[tgt.id] = spec.donate_argnums
            # calls through donors kill their donated args ...
            for node in ast.walk(stmt):
                if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                        and node.func.id in donors):
                    for i in donors[node.func.id]:
                        if i < len(node.args) and isinstance(node.args[i], ast.Name):
                            dead[node.args[i].id] = (node.func.id, node.lineno)
            # ... unless the same statement rebinds the name (x = f(x) idiom)
            for node in ast.walk(stmt):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, (ast.Store, ast.Del))):
                    dead.pop(node.id, None)


# ------------------------------------------------------------------- rule 3

@register
class TracedPythonBranch(Rule):
    name = "traced-python-branch"
    hints = ("jit",)
    hazard = ("Python control flow on a traced array forces concretization: "
              "ConcretizationTypeError under jit, or a silent retrace per "
              "distinct value when the arg reaches the branch as a weak type")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for fn, spec in _jitted_functions(ctx):
            traced = set(_param_names(fn)) - _static_params(fn, spec)
            traced.discard("self")
            for node in _walk_skipping_nested_defs(fn.body):
                test = None
                kind = None
                if isinstance(node, (ast.If, ast.While)):
                    test, kind = node.test, type(node).__name__.lower()
                elif isinstance(node, ast.Assert):
                    test, kind = node.test, "assert"
                elif isinstance(node, ast.IfExp):
                    test, kind = node.test, "conditional expression"
                if test is None:
                    continue
                name = self._traced_operand(test, traced)
                if name:
                    yield self.finding(
                        ctx, node,
                        f"Python {kind} on traced parameter {name!r} of "
                        f"jitted {fn.name}() — use jnp.where/lax.cond or "
                        f"mark the arg static")

    @staticmethod
    def _traced_operand(test: ast.AST, traced: Set[str]) -> Optional[str]:
        """A traced param used as a *value* in the test.  Metadata access
        (``x.shape``, ``x.ndim``, ``len(x)``) is static under jit and
        ``x is None`` is Python-level identity — both are fine and skipped."""
        skip: Set[int] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Attribute):
                for sub in ast.walk(node.value):
                    skip.add(id(sub))
            elif isinstance(node, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                for sub in ast.walk(node):
                    skip.add(id(sub))
            elif (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                  and node.func.id in ("len", "isinstance", "getattr", "hasattr")):
                for sub in ast.walk(node):
                    skip.add(id(sub))
        for node in ast.walk(test):
            if (isinstance(node, ast.Name) and id(node) not in skip
                    and node.id in traced):
                return node.id
        return None


# ------------------------------------------------------------------- rule 4

UNHASHABLE_ANNOTATIONS = {"list", "dict", "set", "List", "Dict", "Set",
                          "typing.List", "typing.Dict", "typing.Set"}


@register
class UnhashableStaticArg(Rule):
    name = "unhashable-static-arg"
    hints = ("static_arg",)
    hazard = ("static_argnums/static_argnames require hashable values — a "
              "list/dict static arg raises ValueError on the first call, or "
              "worse, retraces per call once wrapped in tuple(map(...)) "
              "band-aids")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for fn, spec in _jitted_functions(ctx):
            params = _param_names(fn)
            static = _static_params(fn, spec)
            if not static:
                continue
            defaults = dict(zip(params[len(params) - len(fn.args.defaults):],
                                fn.args.defaults))
            annotations = {a.arg: a.annotation
                           for a in fn.args.posonlyargs + fn.args.args
                           if a.annotation is not None}
            for name in sorted(static):
                ann = annotations.get(name)
                ann_name = self._annotation_name(ctx, ann) if ann else None
                if ann_name in UNHASHABLE_ANNOTATIONS:
                    yield self.finding(
                        ctx, ann or fn,
                        f"static arg {name!r} of {fn.name}() is annotated "
                        f"{ann_name} — unhashable; jit will raise at call "
                        f"time (use a tuple, or trace it)")
                    continue
                default = defaults.get(name)
                if isinstance(default, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                        ast.DictComp, ast.SetComp)):
                    kind = type(default).__name__.lower().replace(
                        "comp", " comprehension")
                    yield self.finding(
                        ctx, default,
                        f"static arg {name!r} of {fn.name}() defaults to a "
                        f"{kind} — unhashable; jit will raise at call time")

    @staticmethod
    def _annotation_name(ctx: FileContext, ann: ast.AST) -> Optional[str]:
        if isinstance(ann, ast.Subscript):  # List[int] → List
            ann = ann.value
        return ctx.resolve(ann)


# ------------------------------------------------------------------- rule 5

@register
class SilentExcept(Rule):
    name = "silent-except"
    hints = ("except",)
    hazard = ("`except Exception: pass` swallows the first signal of real "
              "faults (dead store server, leaked shm ring) — debugging "
              "starts hours later from a hung job instead of a log line")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._broad(ctx, node.type):
                continue
            if all(isinstance(s, ast.Pass) or
                   (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant)
                    and s.value.value is Ellipsis)
                   for s in node.body):
                what = ((ctx.resolve(node.type) or "broad except")
                        if node.type else "bare except")
                yield self.finding(
                    ctx, node,
                    f"{what}: pass — narrow the exception type and log at "
                    f"debug, or pragma with the reason swallowing is correct")

    @staticmethod
    def _broad(ctx: FileContext, type_node: Optional[ast.AST]) -> bool:
        if type_node is None:
            return True
        if isinstance(type_node, ast.Tuple):
            return any(ctx.resolve(e) in ("Exception", "BaseException")
                       for e in type_node.elts)
        return ctx.resolve(type_node) in ("Exception", "BaseException")


# ------------------------------------------------------------------- rule 6

NONDET_STDLIB = {"random", "randint", "randrange", "uniform", "choice",
                 "choices", "shuffle", "sample", "getrandbits",
                 "normalvariate", "gauss", "betavariate", "expovariate"}


@register
class UnseededNondeterminism(Rule):
    name = "unseeded-nondeterminism"
    hazard = ("an unseeded random draw in distributed/ takes a different "
              "value on every host — seeds, schedules, or layer init silently "
              "diverge across replicas (the bugs that surface as loss spikes "
              "three days into a run)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if "distributed/" not in ctx.rel_path:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve(node.func)
            if not name:
                continue
            if name.startswith("random.") and name.split(".")[1] in NONDET_STDLIB:
                yield self.finding(
                    ctx, node,
                    f"{name}() draws from the process-global unseeded stream "
                    f"— replicas diverge; derive from (global seed, rank) "
                    f"instead")
            elif (name.startswith("numpy.random.")
                  and not name.endswith((".seed", ".default_rng", ".RandomState",
                                         ".Generator"))):
                yield self.finding(
                    ctx, node,
                    f"{name}() uses numpy's global unseeded stream — replicas "
                    f"diverge; use a seeded Generator keyed on (seed, rank)")


# ------------------------------------------------------------------- rule 7

IMPORT_TIME_TOUCH = {"jax.devices", "jax.local_devices", "jax.device_count",
                     "jax.local_device_count", "jax.default_backend",
                     "jax.process_index", "jax.process_count"}


@register
class ImportTimeDeviceTouch(Rule):
    name = "import-time-device-touch"
    hints = ("jax", "jnp")
    hazard = ("a jax/jnp call at module scope can initialize the backend "
              "during import — JAX_PLATFORMS / jax.config set afterwards are "
              "silently ignored (the plugin-sitecustomize hang paddle_tpu/"
              "__init__.py works around)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        skip: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                body = (node.body if not isinstance(node, ast.Lambda)
                        else [node.body])
                for stmt in body:
                    for sub in ast.walk(stmt):
                        skip.add(id(sub))
        # `if __name__ == "__main__":` bodies run as a script, after any
        # platform config — not at import time
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.If) and self._is_main_guard(stmt.test):
                for sub in ast.walk(stmt):
                    skip.add(id(sub))
        for node in ast.walk(ctx.tree):
            if id(node) in skip or not isinstance(node, ast.Call):
                continue
            name = ctx.resolve(node.func)
            if not name:
                continue
            if (name in IMPORT_TIME_TOUCH or name.startswith("jax.numpy.")
                    or name.startswith("jnp.")
                    or name.startswith(("jax.random.", "jax.core.",
                                        "jax.eval_shape", "jax.make_array"))):
                yield self.finding(
                    ctx, node,
                    f"{name}() runs at import time (module or default-arg "
                    f"scope) — move it behind a function so platform config "
                    f"can land first")

    @staticmethod
    def _is_main_guard(test: ast.AST) -> bool:
        return (isinstance(test, ast.Compare)
                and isinstance(test.left, ast.Name)
                and test.left.id == "__name__"
                and len(test.ops) == 1 and isinstance(test.ops[0], ast.Eq)
                and len(test.comparators) == 1
                and isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value == "__main__")


# ------------------------------------------------------------------- rule 8

#: Files (relative to paddle_tpu/) whose print() calls are their documented
#: job — CLI entry points, console UIs, reference-parity verbose knobs, the
#: paddle.static.Print op.  NOT a dumping ground; every entry needs a
#: justification and entries with no print() left are themselves findings,
#: so the list stays a real inventory in both directions.
#: Single source of truth: tests/test_no_print.py wraps THIS set.
PRINT_ALLOWLIST = {
    "core/tensor.py",                       # FLAGS-gated eager debug echo
    "distributed/fleet/utils/__init__.py",  # fleet log_util console sink
    "distributed/launch.py",                # CLI entry point
    "hapi/callbacks.py",                    # ProgBarLogger console UI
    "hapi/dynamic_flops.py",                # flops(print_detail=) contract
    "hapi/model_summary.py",                # summary() prints per reference
    "optimizer/lr.py",                      # verbose= knob per reference
    "static/__init__.py",                   # paddle.static.Print op
    "utils/__init__.py",                    # run_check console contract
    "utils/cpp_extension.py",               # verbose build log
}

_PKG_PREFIX = "paddle_tpu/"


@register
class NoPrint(Rule):
    name = "no-print"
    hazard = ("print() in library code bypasses logging — serving hosts "
              "can't route, rate-limit, or silence it (round-6's profiler "
              "print was invisible to log pipelines)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.rel_path.startswith(_PKG_PREFIX):
            return
        rel = ctx.rel_path[len(_PKG_PREFIX):]
        prints = [node for node in ast.walk(ctx.tree)
                  if isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name) and node.func.id == "print"]
        if rel in PRINT_ALLOWLIST:
            if not prints:
                yield Finding(path=ctx.rel_path, line=1, col=1, rule=self.name,
                              message="stale PRINT_ALLOWLIST entry: no print() "
                                      "left in this file — prune the list "
                                      "(paddle_tpu/analysis/rules.py)")
            return
        for node in prints:
            yield self.finding(
                ctx, node,
                "print() in library code — route through logging (see "
                "profiler.stop_profiler) or, for a genuine CLI/console "
                "contract, extend PRINT_ALLOWLIST with a justification")


# ------------------------------------------------------------------- rule 9

#: shard_map spellings (jax's, and the relative-import bare name the
#: spmd compat adapter is bound to — relative imports are opaque to the
#: import map, so the bare name is matched too)
SHARD_MAP_NAMES = {"jax.shard_map", "jax.experimental.shard_map.shard_map",
                   "paddle_tpu.distributed.spmd.shard_map", "shard_map"}


@register
class JitInHotLoop(Rule):
    name = "jit-in-hot-loop"
    hints = ("jit", "shard_map")
    hazard = ("a jax.jit/shard_map wrapper constructed inside a loop — or "
              "rebuilt and invoked per call — is a NEW function object each "
              "time, so the jit cache can never hit: every iteration pays a "
              "fresh trace + XLA compile (the recompile storms the serving "
              "telemetry warns about, now preventable at review time)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        yield from self._in_loops(ctx)
        yield from self._immediately_invoked(ctx)

    def _wrapper_name(self, ctx: FileContext, call: ast.Call) -> Optional[str]:
        """Resolved name when ``call`` constructs a jit/shard_map wrapper
        (direct or through functools.partial); None otherwise."""
        name = ctx.resolve(call.func)
        if name in JIT_NAMES or name in SHARD_MAP_NAMES \
                or (name or "").endswith(".shard_map"):
            return name
        if name in PARTIAL_NAMES or (name or "").endswith(".partial"):
            if call.args:
                inner = ctx.resolve(call.args[0])
                if inner in JIT_NAMES or inner in SHARD_MAP_NAMES:
                    return inner
        return None

    def _in_loops(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                continue
            kind = "while" if isinstance(node, ast.While) else "for"
            for sub in _walk_skipping_nested_defs(node.body + node.orelse):
                if isinstance(sub, ast.Call):
                    name = self._wrapper_name(ctx, sub)
                    if name:
                        yield self.finding(
                            ctx, sub,
                            f"{name}() constructed inside a {kind} loop — "
                            f"each iteration builds (and recompiles) a "
                            f"fresh wrapper; hoist it out of the loop")
                elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # the def statement itself re-executes per iteration,
                    # and its decorators run with it
                    if _jit_decorator_spec(ctx, sub) is not None:
                        yield self.finding(
                            ctx, sub,
                            f"@jit-decorated {sub.name}() defined inside a "
                            f"{kind} loop — the decorator re-wraps (and "
                            f"recompiles) every iteration; define it once "
                            f"outside")

    def _immediately_invoked(self, ctx: FileContext) -> Iterable[Finding]:
        # jax.jit(f)(args) inside a function body: wrapper and cache die
        # with the expression, so every call of the enclosing function
        # recompiles.  Restricted to jit/pjit — shard_map built inside an
        # outer-jitted body traces once and is idiomatic (models/gpt.py);
        # module-scope immediate invocation runs once per import and is
        # likewise exempt.
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in _walk_skipping_nested_defs(fn.body):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Call)):
                    continue
                if _jit_call_spec(ctx, node.func) is not None:
                    yield self.finding(
                        ctx, node,
                        f"jit wrapper built and invoked in one expression "
                        f"inside {fn.name}() — its compile cache is "
                        f"discarded after the call; build the jitted "
                        f"function once outside")


# ------------------------------------------------------------------ rule 10

#: resolved call fullnames that force a device→host round trip (the value
#: must exist on host, so the async dispatch chain drains first)
BLOCKING_FETCH_CALLS = {"numpy.asarray", "jax.device_get",
                        "jax.block_until_ready"}
#: zero-arg method names that block on a device value; ``item`` is the
#: scalar fetch (``items``/``len`` etc. never match)
BLOCKING_FETCH_METHODS = {"block_until_ready", "item"}


@register
class BlockingFetchInLoop(Rule):
    name = "blocking-fetch-in-loop"
    hints = ("asarray", "block_until_ready", ".item(", "device_get")
    hazard = ("a host-blocking fetch (float(np.asarray(x)), np.asarray, "
              ".item(), block_until_ready) inside a for/while training "
              "loop drains the async dispatch chain EVERY iteration — host "
              "and device serialize and the accelerator idles between "
              "steps (the hapi fit loop fetches only at log_freq cadence "
              "for exactly this reason; that site carries the canonical "
              "allow pragma)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        seen: Set[int] = set()   # nested loops: one site reports ONCE
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                continue
            kind = "while" if isinstance(node, ast.While) else "for"
            # float(np.asarray(x)) is ONE fetch: report the float() wrapper
            # and skip its inner asarray so a single site is a single count
            wrapped: Set[int] = set()
            body = list(node.body) + list(node.orelse)
            for sub in _walk_skipping_nested_defs(body):
                if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                        and sub.func.id == "float" and sub.args
                        and isinstance(sub.args[0], ast.Call)
                        and ctx.resolve(sub.args[0].func)
                        in BLOCKING_FETCH_CALLS):
                    wrapped.add(id(sub.args[0]))
            for sub in _walk_skipping_nested_defs(body):
                if not isinstance(sub, ast.Call) or id(sub) in seen:
                    continue
                msg = self._blocking(ctx, sub, wrapped)
                if msg:
                    seen.add(id(sub))
                    yield self.finding(
                        ctx, sub,
                        f"{msg} inside a {kind} loop blocks the host on "
                        f"device results every iteration — hoist the fetch "
                        f"out of the loop, fetch at a log cadence, or "
                        f"pragma the site with why the sync is required")

    @staticmethod
    def _blocking(ctx: FileContext, call: ast.Call,
                  wrapped: Set[int]) -> Optional[str]:
        if (isinstance(call.func, ast.Attribute) and not call.args
                and not call.keywords
                and call.func.attr in BLOCKING_FETCH_METHODS):
            return f".{call.func.attr}() fetch"
        if (isinstance(call.func, ast.Name) and call.func.id == "float"
                and call.args and isinstance(call.args[0], ast.Call)
                and ctx.resolve(call.args[0].func) in BLOCKING_FETCH_CALLS):
            inner = ctx.resolve(call.args[0].func)
            return f"float({inner}(...)) fetch"
        if id(call) in wrapped:
            return None                    # counted via its float() wrapper
        name = ctx.resolve(call.func)
        if name in BLOCKING_FETCH_CALLS:
            return f"{name}() fetch"
        return None


# ------------------------------------------------------------------ rule 11

#: resolved fullnames that pause the current thread between attempts
SLEEP_CALLS = {"time.sleep"}


@register
class UnboundedRetry(Rule):
    name = "unbounded-retry"
    hints = ("sleep",)
    hazard = ("a retry loop that sleeps a CONSTANT between attempts (no "
              "exponential backoff, no jitter) — or retries forever with "
              "no attempt bound — turns one transient fault into a "
              "synchronized retry storm: every client hammers the "
              "recovering service at the same fixed cadence, exactly the "
              "overload the gateway's resilience layer exists to absorb "
              "(docs/RESILIENCE.md retry-budget semantics; gateway.py "
              "ResiliencePolicy.backoff_s is the compliant shape)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        seen: Set[int] = set()   # nested loops: one sleep reports ONCE
        # (ast.walk is outermost-first, so the outermost qualifying loop
        # claims the site — the blocking-fetch-in-loop dedup discipline)
        for node in ast.walk(ctx.tree):
            retry_kind = self._retry_loop_kind(ctx, node)
            if retry_kind is None:
                continue
            body = list(node.body) + list(node.orelse)
            sleeps = [sub for sub in _walk_skipping_nested_defs(body)
                      if isinstance(sub, ast.Call) and id(sub) not in seen
                      and self._constant_sleep(ctx, sub)]
            seen.update(id(call) for call in sleeps)
            if not sleeps:
                continue
            # any exit statement (raise on a deadline, break/return on
            # success or a counted bound) makes the loop escapable; only
            # a while-True with NO exit at all earns the stronger
            # "unbounded" diagnosis — a break-bounded retry is
            # misdiagnosed as unbounded otherwise
            bounded = retry_kind == "for-range" or any(
                isinstance(sub, (ast.Raise, ast.Break, ast.Return))
                for sub in _walk_skipping_nested_defs(body))
            for call in sleeps:
                if not bounded:
                    yield self.finding(
                        ctx, call,
                        "unbounded retry: `while True` with a constant "
                        "time.sleep and no exit at all (no raise/break/"
                        "return) — bound the attempts and use "
                        "exponential backoff with jitter")
                else:
                    yield self.finding(
                        ctx, call,
                        "retry loop sleeps a constant between attempts — "
                        "no backoff, no jitter: synchronized clients "
                        "re-hammer a recovering service in lockstep; use "
                        "exponential backoff with jitter (or pragma why "
                        "a fixed cadence is correct here)")

    @staticmethod
    def _retry_loop_kind(ctx: FileContext, node: ast.AST) -> Optional[str]:
        """'while-true' for ``while True/1:``, 'for-range' for ``for _ in
        range(...)`` (the counted-attempts idiom); None for every other
        loop — a condition-bounded ``while not done():`` poll or a
        data-iteration ``for item in items:`` is pacing work, not
        retrying it."""
        if isinstance(node, ast.While):
            test = node.test
            if isinstance(test, ast.Constant) and bool(test.value):
                return "while-true"
            return None
        if isinstance(node, (ast.For, ast.AsyncFor)):
            it = node.iter
            if isinstance(it, ast.Call) and ctx.resolve(it.func) in (
                    "range", "builtins.range"):
                return "for-range"
            return None
        return None

    @staticmethod
    def _constant_sleep(ctx: FileContext, call: ast.Call) -> bool:
        """``time.sleep(<numeric literal>)`` — a computed argument
        (``base * 2**i``, a jittered ``random.uniform``, a variable) is
        treated as backoff and exempt."""
        if ctx.resolve(call.func) not in SLEEP_CALLS:
            return False
        if len(call.args) != 1 or call.keywords:
            return False
        arg = call.args[0]
        return (isinstance(arg, ast.Constant)
                and isinstance(arg.value, (int, float))
                and not isinstance(arg.value, bool))


# ------------------------------------------------------------------ rule 12

#: resolved fullnames that construct a PartitionSpec directly (jax's
#: spellings plus the top-level ``jax.P`` alias newer jax exposes)
PARTITION_SPEC_NAMES = {"jax.sharding.PartitionSpec", "jax.P",
                        "jax.sharding.partition_spec.PartitionSpec",
                        "jax.experimental.pjit.PartitionSpec"}

#: the one file allowed to construct PartitionSpec: the sharding-rules
#: resolver (distributed/sharding_rules.py) is the single authority for
#: array layouts — every other site goes through its constructors
PARTITION_SPEC_AUTHORITY = "paddle_tpu/distributed/sharding_rules.py"


@register
class RawPartitionSpec(Rule):
    name = "raw-partition-spec"
    hints = ("PartitionSpec",)
    hazard = ("a literal PartitionSpec(...) outside distributed/"
              "sharding_rules.py is a layout decision the resolver cannot "
              "see: it bypasses the rule table (scalar exemption, "
              "divisibility fallback accounting) AND the sharding-rules "
              "digest, so the AOT executable cache cannot invalidate "
              "programs that baked the spec in when layouts change — "
              "route it through sharding_rules' constructors "
              "(make_spec/replicated_spec/batch_spec/...)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel_path == PARTITION_SPEC_AUTHORITY:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve(node.func)
            if name in PARTITION_SPEC_NAMES:
                yield self.finding(
                    ctx, node,
                    f"raw {name}(...) constructed outside "
                    f"sharding_rules.py — use the sharding_rules "
                    f"constructors (make_spec/replicated_spec/batch_spec/"
                    f"...) so the layout rides the rule table and its "
                    f"cache-invalidation digest")


# ------------------------------------------------------------------ rule 13

#: resolved fullnames that walk the live-array set directly
LIVE_ARRAYS_NAMES = {"jax.live_arrays", "jax.lib.xla_bridge.live_arrays"}

#: the one file allowed raw memory introspection: the memory ledger
#: (telemetry_memory.py) is the single accounting point — every census,
#: classifier, and allocator-stats read routes through it
MEMORY_INTROSPECTION_AUTHORITY = "paddle_tpu/telemetry_memory.py"


@register
class RawMemoryIntrospection(Rule):
    name = "raw-memory-introspection"
    hints = ("live_arrays", "memory_stats")
    hazard = ("a direct jax.live_arrays() walk or device .memory_stats() "
              "read outside telemetry_memory.py is a second memory "
              "accounting point: its bytes bypass the ledger's pool "
              "attribution, so the conservation invariant (sum of pools "
              "== census total) can no longer be audited, and ad-hoc "
              "walks over thousands of live arrays on a hot path are a "
              "latency hazard the ledger's census batching exists to "
              "contain — route reads through telemetry_memory "
              "(live_array_census / device_allocator_stats / "
              "MemoryLedger.census)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel_path == MEMORY_INTROSPECTION_AUTHORITY:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve(node.func)
            if name in LIVE_ARRAYS_NAMES:
                yield self.finding(
                    ctx, node,
                    f"raw {name}() walk outside telemetry_memory.py — "
                    f"use telemetry_memory.live_array_census (or a "
                    f"MemoryLedger census) so the bytes land in the "
                    f"pool ledger's conservation audit")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "memory_stats"):
                yield self.finding(
                    ctx, node,
                    "raw device .memory_stats() read outside "
                    "telemetry_memory.py — use telemetry_memory."
                    "device_allocator_stats (utils.stats."
                    "device_memory_stats delegates there) so allocator "
                    "reads share one accounting point")
