"""no-print positive: library code printing to stdout.  (Fixture: parsed
by tpulint, never imported.)"""


def report(stats):
    # trips: serving hosts can't route/rate-limit/silence stdout
    print(f"processed {stats} requests")
