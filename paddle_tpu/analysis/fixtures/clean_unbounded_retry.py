"""unbounded-retry near-misses: every loop here is silent.

Backoff that GROWS (with jitter) is the compliant retry shape; a
condition-bounded poll or data iteration paces work rather than
retrying it, and a ``while True`` event loop without sleeps is not a
retry at all.
"""

import random
import time


def retry_with_backoff(fetch):
    for attempt in range(5):
        result = fetch()
        if result is not None:
            return result
        time.sleep(0.1 * (2 ** attempt) + random.uniform(0.0, 0.05))
    return None


def retry_with_variable_delay(fetch, delay):
    for _attempt in range(3):
        result = fetch()
        if result is not None:
            return result
        time.sleep(delay)                 # computed by the caller
    return None


def poll_until(done):
    while not done():                     # condition-bounded poll loop
        time.sleep(0.1)


def paced_iteration(items, handle):
    for item in items:                    # data iteration, not retries
        handle(item)
        time.sleep(0.2)


def event_loop(queue, handle):
    while True:                           # no sleeps: not a retry loop
        item = queue.get()
        if item is None:
            return
        handle(item)
