"""unseeded-nondeterminism near-misses that must stay silent.  (Fixture:
parsed by tpulint, never imported.)"""

import numpy as np


def jitter(seed: int, rank: int) -> float:
    # seeded Generator keyed on (seed, rank): deterministic per replica
    gen = np.random.default_rng((seed, rank))
    return float(gen.uniform(0.0, 0.1))
