"""Path-scoped fixtures: unseeded-nondeterminism only fires on files whose
path contains ``distributed/``.  Parsed, never imported."""
