"""unseeded-nondeterminism positives (path contains distributed/, where
every unseeded draw is a replica-divergence hazard).  (Fixture: parsed by
tpulint, never imported.)"""

import random

import numpy as np


def pick_port():
    # trips: every host picks a different port — rendezvous splits
    return 20000 + random.randint(0, 1000)


def jitter():
    # trips: numpy global stream differs per process
    return np.random.uniform(0.0, 0.1)
