"""Fixture: host-blocking fetches inside training loops (one finding per
marked line — float(np.asarray(...)) is ONE combined fetch)."""
import jax
import numpy as np


def train(step, state, batches):
    losses = []
    for batch in batches:
        state, loss = step(state, batch)
        losses.append(float(np.asarray(loss)))   # BAD: combined fetch
        loss.block_until_ready()                 # BAD: method sync
        scalar = loss.item()                     # BAD: scalar fetch
        jax.block_until_ready(state)             # BAD: function sync
        host = np.asarray(loss)                  # BAD: bare fetch
        del scalar, host
    while losses:
        pending = losses.pop()
        _ = jax.device_get(pending)              # BAD: while-loop fetch
    return losses
