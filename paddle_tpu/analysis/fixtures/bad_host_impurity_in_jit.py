"""host-impurity-in-jit positives: host state read under trace.  (Fixture:
parsed by tpulint, never imported — see fixtures/__init__.py.)"""

import functools
import os
import time

import jax


@jax.jit
def stamp(x):
    # trips: one wall-clock value is baked into the compiled program
    return x * time.time()


@functools.partial(jax.jit, donate_argnums=(0,))
def scaled(x):
    # trips twice: env read latched at trace time, print runs once ever
    lr = float(os.environ.get("LR", "1e-3"))
    print("tracing scaled")
    return x * lr
