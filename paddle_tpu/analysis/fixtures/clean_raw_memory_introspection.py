"""raw-memory-introspection near-misses: every read here routes through
the memory ledger's sanctioned surface.  (Fixture: parsed by tpulint,
never imported.)

The census classifier and the allocator-stats delegate are the single
accounting point; merely naming the functions — a docstring, a variable
called live_arrays, an unrelated attribute — is not a memory read.
"""

from paddle_tpu.telemetry_memory import (device_allocator_stats,
                                         live_array_census)


def census_backed(params, opt):
    # the sanctioned walk: one classification, conservation auditable
    return live_array_census({"params": params, "opt": opt})


def allocator_backed():
    return device_allocator_stats(0)


def unrelated_names(stats):
    live_arrays = [a for a in stats if a]          # a variable, not a call
    memory_stats = {"peak": 0}                     # a dict, not a method
    return live_arrays, memory_stats["peak"]
