"""unhashable-static-arg near-misses that must stay silent.  (Fixture:
parsed by tpulint, never imported.)"""

from functools import partial
from typing import Tuple

import jax


@partial(jax.jit, static_argnums=(1,))
def gather(x, idx: Tuple[int, ...]):
    # tuples hash — silent
    return x


@partial(jax.jit, static_argnames=("mode",))
def run(x, mode="greedy", weights=None):
    # `weights` is traced, not static: its annotation/default is irrelevant
    return x
