"""traced-python-branch positives: Python control flow on traced arrays.
(Fixture: parsed by tpulint, never imported.)"""

import jax


@jax.jit
def relu_or_zero(x, threshold):
    # trips: ConcretizationTypeError at trace time (or a retrace per value)
    if x > threshold:
        return x
    return x * 0


@jax.jit
def drain(n):
    total = 0
    # trips: Python while cannot iterate on a tracer
    while n > 0:
        total = total + 1
        n = n - 1
    # trips: assert concretizes the traced value
    assert n == 0
    return total
