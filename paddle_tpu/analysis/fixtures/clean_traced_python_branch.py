"""traced-python-branch near-misses that must stay silent.  (Fixture:
parsed by tpulint, never imported.)"""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("training",))
def dropout(x, training):
    # static arg: Python branch is the supported specialization idiom
    if training:
        return x * 0.5
    return x


@jax.jit
def safe(x, y=None):
    # `is None` is Python identity, decided at trace time by design
    if y is None:
        y = jnp.zeros_like(x)
    # shape/ndim metadata is static under jit
    if x.ndim > 2:
        x = x.reshape(-1, x.shape[-1])
    return x + y
