"""Pragma demo: correctly suppressed violations must yield ZERO findings —
this file doubles as the gate's live test of the suppression path.
(Fixture: parsed by tpulint, never imported.)"""


def closing(sock):
    try:
        sock.close()
    except Exception:  # tpulint: disable=silent-except(GC-path close; socket may already be dead and there is nothing to log to)
        pass


def closing_above(sock):
    try:
        sock.close()
    # tpulint: disable=silent-except(pragma on the comment line above the handler also covers it)
    except Exception:
        pass
