"""Whole-program fixture corpus for the ``--program`` concurrency passes.

Unlike the per-file fixtures one directory up, these are PACKAGES: the
bug only exists across files (thread entry in one module, shared state in
another), which is exactly what the whole-program model exists to see.
One ``bad_*``/``clean_*`` package pair per pass:

- ``bad_disagg``/``clean_disagg`` — guarded-by-race: a dict written under
  its lock on the tick path but iterated bare from an HTTP scrape handler
  in a different module (the ``gateway._disagg`` shape);
- ``bad_firing``/``clean_firing`` — unguarded-shared-state: a set churned
  from monitor subscriber callbacks with no lock anywhere (the pre-fix
  ``autoscaler._firing`` shape);
- ``bad_publish.py``/``clean_publish.py`` — publish-before-init:
  ``__init__`` starts a thread before assigning the state it reads;
- ``bad_annotation.py``/``clean_annotation.py`` — bad-guarded-by: a
  ``# guarded-by:`` declaration naming a lock the class never defines.

Parsed, never imported — same contract as the rest of the corpus.  The CI
sweep lints these in place, so every program rule keeps a baselined
true-positive: a pass going silently blind shows up as a STALE baseline.
"""
