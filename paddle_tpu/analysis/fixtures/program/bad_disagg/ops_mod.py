"""The thread-entry side: a BaseHTTPRequestHandler subclass — every
method runs on a server thread — reaching ``MiniGateway.snapshot`` in the
sibling module through a typed local."""

from http.server import BaseHTTPRequestHandler

from .gateway_mod import MiniGateway


class ScrapeHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        gw: "MiniGateway" = self.server.gw
        body = str(gw.snapshot()).encode()
        self.send_response(200)
        self.end_headers()
        self.wfile.write(body)
