"""The shared-state side: ``_jobs`` is written under ``_jobs_lock`` on the
tick path — the pass must infer the guard from those locked writes — but
``snapshot()`` iterates it bare, and the handler module drives
``snapshot()`` from an HTTP server thread."""

import threading


class MiniGateway:
    def __init__(self):
        self._jobs_lock = threading.Lock()
        self._jobs = {}

    def step(self):
        with self._jobs_lock:
            self._jobs[len(self._jobs)] = "migrating"

    def finish(self, job_id):
        with self._jobs_lock:
            self._jobs.pop(job_id, None)

    def snapshot(self):
        # trips guarded-by-race: iterating the guarded dict without the
        # lock, on a path the scrape thread reaches
        return {k: v for k, v in self._jobs.items()}
