"""guarded-by-race positive: locked tick-path writes, bare scrape-path
iteration, across two modules.  (Fixture: parsed, never imported.)"""
