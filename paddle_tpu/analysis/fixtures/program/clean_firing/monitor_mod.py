"""Same entry side as the bad twin."""


class MiniMonitor:
    def __init__(self):
        self._subs = []

    def subscribe(self, fn):
        self._subs.append(fn)

    def evaluate(self, name, active):
        for fn in list(self._subs):
            fn(name, active)
