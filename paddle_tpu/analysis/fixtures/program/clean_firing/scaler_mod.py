"""Same churn as the bad twin, every access under ``_firing_lock`` —
nested ``with`` on the callback path must not confuse the lock stack."""

import threading

from .monitor_mod import MiniMonitor


class MiniScaler:
    def __init__(self, monitor: MiniMonitor):
        self._firing_lock = threading.Lock()
        self._firing = set()
        self._log_lock = threading.Lock()
        monitor.subscribe(self._on_alert)

    def _on_alert(self, name, active):
        with self._log_lock:
            with self._firing_lock:     # nested with: inner lock counts
                if active:
                    self._firing.add(name)
                else:
                    self._firing.discard(name)

    def firing(self):
        with self._firing_lock:
            return sorted(self._firing)
