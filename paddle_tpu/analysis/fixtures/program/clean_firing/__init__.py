"""unguarded-shared-state near-miss: same subscriber churn, but the set
rides a lock — the post-fix autoscaler shape; must stay silent.
(Fixture: parsed, never imported.)"""
