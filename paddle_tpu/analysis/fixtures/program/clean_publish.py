"""publish-before-init near-miss: state first, publish last — must stay
silent.  (Fixture: parsed, never imported.)"""

import threading


class CleanPublisher:
    def __init__(self):
        self._results = []
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        # read-only so ONLY the publish ordering is at fault here
        print_len = len(self._results)
        del print_len
