"""unguarded-shared-state positive: subscriber-callback set churn with no
lock anywhere, across two modules.  (Fixture: parsed, never imported.)"""
