"""The state side: ``_firing`` is a bare set churned from the subscriber
callback and iterated on the main path — the pre-fix autoscaler shape."""

from .monitor_mod import MiniMonitor


class MiniScaler:
    def __init__(self, monitor: MiniMonitor):
        self._firing = set()
        monitor.subscribe(self._on_alert)

    def _on_alert(self, name, active):
        # trips unguarded-shared-state: mutate on the subscriber thread
        if active:
            self._firing.add(name)
        else:
            self._firing.discard(name)

    def firing(self):
        # trips unguarded-shared-state: iterate while the callback churns
        return sorted(self._firing)
