"""The entry side: ``subscribe()`` registers callbacks that run on
whatever thread drives ``evaluate()`` — the subscriber seed."""


class MiniMonitor:
    def __init__(self):
        self._subs = []

    def subscribe(self, fn):
        self._subs.append(fn)

    def evaluate(self, name, active):
        for fn in list(self._subs):
            fn(name, active)
