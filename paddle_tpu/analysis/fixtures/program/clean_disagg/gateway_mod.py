"""Same state and guard as the bad twin; every access holds the lock,
including through a local alias and a private helper only ever called
under the lock (the inherited-locks corner)."""

import threading


class MiniGateway:
    def __init__(self):
        self._jobs_lock = threading.Lock()
        self._jobs = {}

    def step(self):
        with self._jobs_lock:
            self._jobs[len(self._jobs)] = "migrating"
            self._note()

    def finish(self, job_id):
        lk = self._jobs_lock            # alias form must still count
        with lk:
            self._jobs.pop(job_id, None)

    def _note(self):
        # called only with _jobs_lock held: inherited, not a race
        self._jobs["last"] = "noted"

    def snapshot(self):
        with self._jobs_lock:
            return {k: v for k, v in self._jobs.items()}
