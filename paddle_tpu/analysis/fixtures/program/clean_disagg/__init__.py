"""guarded-by-race near-miss: same two-module shape as ``bad_disagg``,
but the scrape path snapshots under the lock — must stay silent.
(Fixture: parsed, never imported.)"""
