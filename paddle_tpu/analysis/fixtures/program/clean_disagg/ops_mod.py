"""Same thread entry as the bad twin — the silence must come from the
locking in ``gateway_mod``, not from missing reachability."""

from http.server import BaseHTTPRequestHandler

from .gateway_mod import MiniGateway


class ScrapeHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        gw: "MiniGateway" = self.server.gw
        body = str(gw.snapshot()).encode()
        self.send_response(200)
        self.end_headers()
        self.wfile.write(body)
