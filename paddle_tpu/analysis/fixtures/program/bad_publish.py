"""publish-before-init positive: ``__init__`` starts the worker thread
BEFORE assigning the state the worker reads — the thread can observe the
half-constructed object.  (Fixture: parsed, never imported.)"""

import threading


class BadPublisher:
    def __init__(self):
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()
        self._results = []      # trips: assigned after self was published

    def _run(self):
        # read-only so ONLY the publish ordering is at fault here
        print_len = len(self._results)
        del print_len
