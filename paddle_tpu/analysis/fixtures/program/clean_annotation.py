"""bad-guarded-by near-misses: a declaration naming a real lock, and a
deliberate ``none`` — both must stay silent.  (Fixture: parsed, never
imported.)"""

import threading


class CleanAnnotation:
    def __init__(self):
        self._items_lock = threading.Lock()
        self._items = {}    # guarded-by: _items_lock
        self._scratch = []  # guarded-by: none (per-call scratch, never shared)
