"""bad-guarded-by positive: the declaration names a lock the class never
defines — undetectable discipline rots.  (Fixture: parsed, never
imported.)"""


class BadAnnotation:
    def __init__(self):
        self._items = {}    # guarded-by: _items_lock
