"""Near-miss fixture: loops that touch device values WITHOUT blocking the
host per iteration — metadata access, post-loop fetches, fetch code inside
nested defs (only executed if called), and dict ``.items()`` (not the
Tensor ``.item()`` scalar fetch)."""
import numpy as np


def train(step, state, batches):
    loss = None
    for batch in batches:
        state, loss = step(state, batch)
        shape = loss.shape            # metadata is free under async dispatch
        del shape
    return float(np.asarray(loss))    # ONE fetch, after the loop


def table(rows):
    out = []
    for row in rows:
        out.extend(row.items())       # dict items(), not a scalar fetch
        def fetch():                  # defined per row, never called here
            return np.asarray(row)
        out.append(fetch)
    return out
