"""unbounded-retry fixture: retry loops with constant sleeps.

Case 1 loops forever with a fixed cadence and no exit at all (the
strong "unbounded" diagnosis); cases 2-4 can exit (success break,
attempt bound, deadline raise) but still re-hammer at a constant
interval — synchronized clients hit the recovering service in lockstep.
"""

import time


def resubmit_forever(fetch, sink):
    while True:                           # no exit at all: unbounded
        result = fetch()
        if result is not None:
            sink.append(result)
        time.sleep(0.5)                   # BAD: unbounded + constant


def retry_until_success(fetch):
    while True:                           # exits only on success
        result = fetch()
        if result is not None:
            break
        time.sleep(0.5)                   # BAD: constant cadence
    return result


def retry_counted(fetch):
    for _attempt in range(5):             # bounded, but constant cadence
        result = fetch()
        if result is not None:
            return result
        time.sleep(1.0)                   # BAD: no backoff/jitter
    return None


def retry_deadline(fetch, deadline):
    while True:
        result = fetch()
        if result is not None:
            return result
        if time.time() > deadline:
            raise TimeoutError("gave up")
        time.sleep(0.2)                   # BAD: bounded, constant cadence
