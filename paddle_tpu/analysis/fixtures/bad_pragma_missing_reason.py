"""bad-pragma positive: a reason-less pragma suppresses nothing and is
itself reported.  (Fixture: parsed by tpulint, never imported.)"""


def closing(sock):
    try:
        sock.close()
    except Exception:  # tpulint: disable=silent-except
        pass
