"""raw-partition-spec positives.  (Fixture: parsed by tpulint, never
imported.)

Every spelling of a literal PartitionSpec construction outside
distributed/sharding_rules.py: the aliased import, the attribute chain,
and the unaliased name — each one is a layout decision the rule table
(and its AOT cache-invalidation digest) cannot see.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec
from jax.sharding import PartitionSpec as P


def aliased_spec(mesh):
    return NamedSharding(mesh, P("data", None))     # BAD: aliased P(...)


def attribute_chain_spec(mesh):
    spec = jax.sharding.PartitionSpec("model")      # BAD: dotted spelling
    return NamedSharding(mesh, spec)


def unaliased_spec():
    return PartitionSpec(None, "data")              # BAD: unaliased name
