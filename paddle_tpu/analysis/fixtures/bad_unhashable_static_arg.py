"""unhashable-static-arg positives.  (Fixture: parsed by tpulint, never
imported.)"""

from functools import partial

import jax


@partial(jax.jit, static_argnums=(1,))
def gather(x, idx: list):
    # trips: static args are dict-keys of the compile cache; a list raises
    # ValueError on the first call
    return x


@partial(jax.jit, static_argnames=("cfg",))
def run(x, cfg={}):
    # trips: dict default for a static name
    return x
