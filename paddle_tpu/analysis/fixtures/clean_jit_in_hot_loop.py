"""Near-miss: wrappers built ONCE (module or builder scope) and reused
across iterations and calls — the jit cache hits from the second use on."""
import jax

_double = jax.jit(lambda x: x * 2)            # module scope: once per import


def per_batch(batches):
    return [_double(b) for b in batches]      # reuse inside the loop


def build_step():
    @jax.jit
    def step(a):
        return a + 1
    return step


def run(xs):
    step = build_step()                       # constructed once, hoisted
    outs = []
    for x in xs:
        outs.append(step(x))
    return outs


def lowered_aot(x):
    # .lower() on a fresh wrapper is the AOT path, not a per-call dispatch
    return jax.jit(lambda a: a).lower(x).compile()
