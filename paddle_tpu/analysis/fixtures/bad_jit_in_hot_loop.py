"""Fixture: jit/shard_map wrappers constructed per iteration / per call —
every construction is a new function object, so the jit cache never hits
and each one pays a fresh trace + XLA compile."""
import functools

import jax
from jax.experimental.shard_map import shard_map


def per_batch(batches, mesh, spec):
    outs = []
    for b in batches:
        f = jax.jit(lambda x: x * 2)          # BAD: new wrapper per batch
        outs.append(f(b))
    i = 0
    while i < 3:
        g = shard_map(lambda x: x, mesh=mesh,  # BAD: rebuilt per spin
                      in_specs=spec, out_specs=spec)
        outs.append(g(batches[0]))
        i += 1
    return outs


def per_call(x):
    return jax.jit(lambda a: a + 1)(x)        # BAD: rebuilt on every call


def decorated_per_iteration(xs):
    outs = []
    for x in xs:
        @functools.partial(jax.jit, donate_argnums=())   # BAD: decorator
        def step(a):                                     # re-wraps per spin
            return a * 2
        outs.append(step(x))
    return outs
