"""no-print near-miss that must stay silent.  (Fixture: parsed by tpulint,
never imported.)"""

import logging

logger = logging.getLogger(__name__)


def report(stats):
    logger.info("processed %s requests", stats)
