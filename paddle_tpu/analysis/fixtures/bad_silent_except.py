"""silent-except positives.  (Fixture: parsed by tpulint, never
imported.)"""


def best_effort_close(sock):
    try:
        sock.close()
    except Exception:
        # trips: the first signal of a real fault evaporates here
        pass


def doubly_silent(fn):
    try:
        fn()
    except BaseException:
        ...
