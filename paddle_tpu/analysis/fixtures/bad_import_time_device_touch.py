"""import-time-device-touch positives.  (Fixture: parsed by tpulint, NEVER
imported — importing this file would initialize a JAX backend.)"""

import jax
import jax.numpy as jnp

# trips: array construction at module scope initializes the backend during
# import, before JAX_PLATFORMS/jax.config can land
_ZERO = jnp.zeros((8,))

# trips: device query at import time latches the platform
NUM_DEVICES = jax.device_count()


def pad(x, fill=jnp.zeros(())):
    # trips: default args evaluate at import time too
    return x + fill
