"""raw-partition-spec near-misses: every layout here rides the rule
table.  (Fixture: parsed by tpulint, never imported.)

Specs come from sharding_rules' constructors (the sanctioned authority),
and merely NAMING PartitionSpec — a type annotation, an isinstance
check — is not a layout decision.
"""

from jax.sharding import NamedSharding, PartitionSpec
from paddle_tpu.distributed.sharding_rules import (batch_spec, make_spec,
                                                   replicated_spec)


def resolver_backed_specs(mesh):
    return (NamedSharding(mesh, make_spec("data", None)),
            NamedSharding(mesh, replicated_spec()),
            NamedSharding(mesh, batch_spec(mesh)))


def spec_predicate(spec) -> bool:
    # referencing the type without constructing it is fine
    return isinstance(spec, PartitionSpec)


def annotated(spec: PartitionSpec) -> PartitionSpec:
    return spec
