"""donated-arg-reuse near-misses that must stay silent.  (Fixture: parsed
by tpulint, never imported.)"""

import jax


def _apply(params, grads):
    return params


def train_step(params, grads):
    # rebinding the donated name in the same statement is THE donation
    # idiom — silent
    step = jax.jit(_apply, donate_argnums=(0,))
    params = step(params, grads)
    return params


def undonated(params, grads):
    # no donate_argnums: reuse after call is fine — silent
    step = jax.jit(_apply)
    new_params = step(params, grads)
    return new_params, params
