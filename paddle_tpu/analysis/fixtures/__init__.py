"""tpulint fixture corpus — intentionally hazardous snippets, one pair per
rule (``bad_<rule>.py`` must trip exactly that rule; ``clean_<rule>.py`` is
the near-miss that must stay silent).

These files are PARSED, never imported: the unit tests
(tests/test_tpulint_rules.py) lint them as text, and the CI gate lints them
in place so every rule has a baselined true-positive exercised on every
run — the ratchet machinery itself would catch a rule silently going blind.
Do not import submodules of this package; several would touch devices or
crash by design.
"""
