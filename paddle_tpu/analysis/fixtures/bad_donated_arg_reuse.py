"""donated-arg-reuse positive: donated buffer read after the call.
(Fixture: parsed by tpulint, never imported.)"""

import jax


def _apply(params, grads):
    return params


def train_step(params, grads):
    step = jax.jit(_apply, donate_argnums=(0,))
    new_params = step(params, grads)
    # trips: `params` was donated on the line above — its device buffer is
    # freed/aliased; reading it returns garbage on TPU
    norm = sum(jax.tree_util.tree_leaves(params))
    return new_params, norm
