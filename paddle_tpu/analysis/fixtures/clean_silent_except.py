"""silent-except near-misses that must stay silent.  (Fixture: parsed by
tpulint, never imported.)"""

import logging

logger = logging.getLogger(__name__)


def narrowed(sock):
    try:
        sock.close()
    except OSError:
        # narrowed type: deliberate, reviewable, silent for tpulint
        pass


def logged(fn):
    try:
        fn()
    except Exception:
        logger.debug("best-effort call failed", exc_info=True)
