"""host-impurity-in-jit near-misses that must stay silent.  (Fixture:
parsed by tpulint, never imported.)"""

import time

import jax
import jax.numpy as jnp


@jax.jit
def pure(x):
    # jax.random is functional, not host randomness — silent
    return x * jnp.float32(2.0)


def host_side(x):
    # host clock OUTSIDE jit is legitimate (telemetry does this everywhere)
    return x, time.time()
