"""import-time-device-touch near-misses that must stay silent.  (Fixture:
parsed by tpulint, never imported.)"""

import jax
import jax.numpy as jnp

# attribute READS (dtypes, submodule aliases) don't init a backend — silent
f32 = jnp.float32


def zeros():
    # the same calls behind a function run after config — silent
    return jnp.zeros((8,)), jax.device_count()
