"""raw-memory-introspection positives.  (Fixture: parsed by tpulint,
never imported.)

Every spelling of a direct memory read outside telemetry_memory.py:
the live-array walk (bare and dotted) and the PJRT allocator-stats
method — each one is a second accounting point whose bytes bypass the
memory ledger's pool attribution.
"""

import jax
from jax import live_arrays


def bare_walk():
    return sum(a.nbytes for a in live_arrays())     # BAD: imported name


def dotted_walk():
    return len(jax.live_arrays())                   # BAD: dotted spelling


def allocator_read():
    dev = jax.local_devices()[0]
    return dev.memory_stats()                       # BAD: raw stats read
