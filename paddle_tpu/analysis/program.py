"""tpulint whole-program model — the cross-file substrate under
:mod:`paddle_tpu.analysis.concurrency`.

The per-file rules in :mod:`.rules` see one AST at a time; the concurrency
bug class this repo keeps hand-finding (`gateway._disagg` iterated by an
ops-server scrape thread while ``step()`` mutates it, the autoscaler's
``_firing`` set churned from SLO subscriber callbacks) is only visible
across files: the thread ENTRY lives in one module (``ops_server``'s
``ThreadingHTTPServer`` handler, ``SLOMonitor.subscribe``), the shared
state in another.  This module builds the project-wide model those passes
run on:

- module map: dotted module name → parsed :class:`ModuleInfo` (imports
  resolved, including relative imports — fixture packages use them);
- class map: ``module.Class`` → :class:`ClassInfo` (methods, resolved
  bases, ``self._*`` attribute accesses WITH the lock set held at each
  access site, lock inventory, ``# guarded-by:`` annotations);
- call graph over methods/functions: ``self.m()``, constructor-typed and
  annotation-typed attributes/locals (``ops: "OpsServer" = ...``),
  imported names, and a unique-method-name fallback for duck-typed calls
  (an attr call resolves to ``Cls.m`` only when exactly ONE program class
  defines ``m`` — over-approximate on purpose: reachability wants recall,
  the ratchet baseline absorbs precision misses).

Deliberately stdlib-only (``ast``/``re``) like the rest of the package:
the ``--program`` sweep re-parses the tree in ~1 s and never imports JAX.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import iter_py_files

# access kinds, ordered by how loudly they race
READ = "read"          # plain load of the attribute value
ITERATE = "iterate"    # for/comprehension over it, list()/sorted()/dict() of it
WRITE = "write"        # rebinding assignment: self._x = ...
MUTATE = "mutate"      # in-place: .add()/.pop()/augassign/subscript-store/del

#: container methods that mutate the receiver in place
_MUTATOR_METHODS = {
    "add", "append", "appendleft", "clear", "discard", "extend", "extendleft",
    "insert", "pop", "popitem", "popleft", "remove", "setdefault", "update",
    "__setitem__", "__delitem__",
}
#: container methods whose return value walks the container (racy to call
#: while another thread mutates — dict.items() during insert raises)
_ITERATOR_METHODS = {"items", "keys", "values", "copy", "most_common"}
#: builtins that iterate their (sole relevant) argument
_ITERATING_BUILTINS = {"list", "sorted", "tuple", "set", "frozenset", "dict",
                       "sum", "min", "max", "any", "all", "enumerate"}

#: attribute names that look like locks even without a visible
#: ``threading.Lock()`` assignment (conservative: suffix match)
_LOCKISH = re.compile(r"(?:^|_)r?lock$")

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock",
               "threading.Condition", "Condition"}

#: ``# guarded-by: <lock>`` annotation on the line initializing an attr —
#: declares the guard (``none`` declares "deliberately unguarded" and
#: silences the race passes for that attr; state why in the trailing text)
GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*|none)\b")
_SELF_ATTR_ASSIGN_RE = re.compile(r"self\.(\w+)\s*(?::[^=]+)?=")

#: method names never resolved through the unique-name fallback — dunders
#: plus names whose duck-typed ubiquity makes "defined once" a coincidence
_NEVER_UNIQUE = {"__init__", "__enter__", "__exit__", "__call__", "get",
                 "put", "close", "start", "stop", "run", "step", "submit"}


@dataclasses.dataclass
class Access:
    """One ``self._attr`` touch: where, what kind, and which of the
    enclosing class's locks were held (``with self._lock:`` nesting,
    local aliases of ``self._*lock`` included)."""

    attr: str
    kind: str
    locks: frozenset
    line: int
    col: int


@dataclasses.dataclass
class CallSite:
    """Unresolved call edge recorded while scanning a body; resolved
    against the finished program by :meth:`Program.resolve_calls`.

    shape ∈ {"self" (self.m()), "typed" (x.m() with x: Cls known),
    "name" (dotted fullname through imports), "unique" (o.m() untyped)}.
    ``locks`` is the lock set held AT the call site — the guarded-by pass
    uses it to infer that a private helper called only under a lock runs
    with that lock held (the ``emit() → _append()`` shape).
    """

    shape: str
    name: str                  # method/function name
    qualifier: str = ""        # class qualname for "typed", dotted for "name"
    line: int = 0
    locks: frozenset = frozenset()


@dataclasses.dataclass
class Seed:
    """A concurrent entry point: ``target`` is a CallSite-shaped reference
    to the callable that runs off the constructing thread."""

    label: str                 # thread-target | pool-task | subscriber | ...
    target: CallSite
    line: int


class FunctionInfo:
    """One function or method body's scan results."""

    def __init__(self, module: "ModuleInfo", node: ast.AST,
                 cls: Optional["ClassInfo"] = None):
        self.module = module
        self.cls = cls
        self.node = node
        self.name = node.name
        self.accesses: List[Access] = []
        self.calls: List[CallSite] = []
        self.seeds: List[Seed] = []
        #: thread labels this body is reachable from (filled by propagate)
        self.thread_labels: Set[str] = set()

    @property
    def qualname(self) -> str:
        if self.cls is not None:
            return f"{self.cls.qualname}.{self.name}"
        return f"{self.module.name}.{self.name}"

    def __repr__(self):
        return f"<fn {self.qualname}>"


class ClassInfo:
    def __init__(self, module: "ModuleInfo", node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.qualname = f"{module.name}.{node.name}"
        #: resolved dotted base names (through imports); program classes
        #: among them are linked in Program.finish()
        self.base_names: List[str] = []
        self.bases: List["ClassInfo"] = []
        self.methods: Dict[str, FunctionInfo] = {}
        #: attrs assigned a Lock()/RLock()/Condition() (or *_lock names)
        self.lock_attrs: Set[str] = set()
        #: attr → class qualname, from ``self._x = Cls(...)`` / annotations
        self.attr_types: Dict[str, str] = {}
        #: attr → lock attr name (or "none"), from guarded-by annotations
        self.guarded_by: Dict[str, Tuple[str, int]] = {}
        #: __init__ publication point (stmt line) → seeds fired there
        self.init_publishes: List[Tuple[int, Seed]] = []
        #: attr → first-assignment line inside __init__
        self.init_assign_line: Dict[str, int] = {}

    def method(self, name: str) -> Optional[FunctionInfo]:
        c: Optional[ClassInfo] = self
        seen = set()
        while c is not None and c.qualname not in seen:
            seen.add(c.qualname)
            if name in c.methods:
                return c.methods[name]
            c = c.bases[0] if c.bases else None
        return None

    def guard_declaration(self, attr: str) -> Optional[Tuple[str, int]]:
        """# guarded-by: declaration for ``attr``, walking base classes —
        a container declared on ``Layer.__init__``'s line covers every
        subclass that mutates it."""
        c: Optional[ClassInfo] = self
        seen = set()
        while c is not None and c.qualname not in seen:
            seen.add(c.qualname)
            if attr in c.guarded_by:
                return c.guarded_by[attr]
            c = c.bases[0] if c.bases else None
        return None

    def all_lock_attrs(self) -> Set[str]:
        """Lock inventory including inherited locks."""
        out: Set[str] = set()
        c: Optional[ClassInfo] = self
        seen = set()
        while c is not None and c.qualname not in seen:
            seen.add(c.qualname)
            out.update(c.lock_attrs)
            c = c.bases[0] if c.bases else None
        return out

    def all_accesses(self) -> Iterable[Tuple[FunctionInfo, Access]]:
        for m in self.methods.values():
            for a in m.accesses:
                yield m, a

    def is_http_handler(self) -> bool:
        """BaseHTTPRequestHandler subclasses (by resolved base name or the
        do_GET/do_POST shape): every method runs on a server thread."""
        for b in self.base_names:
            if "HTTPRequestHandler" in b or "StreamRequestHandler" in b:
                return True
        return any(n.startswith("do_") for n in self.methods)

    def __repr__(self):
        return f"<class {self.qualname}>"


class ModuleInfo:
    def __init__(self, name: str, rel_path: str, source: str, tree: ast.AST):
        self.name = name
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.imports = _module_imports(tree, name, rel_path)
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}

    def resolve_name(self, node: ast.AST) -> Optional[str]:
        """Dotted fullname of a Name/Attribute chain through this module's
        imports (relative imports resolved); None when dynamic."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.imports.get(node.id, node.id)
        parts.append(head)
        return ".".join(reversed(parts))


def _module_imports(tree: ast.AST, module_name: str,
                    rel_path: str) -> Dict[str, str]:
    """Local name → dotted fullname, RELATIVE imports included (the
    engine's per-file map skips them; fixture packages and intra-package
    code need them to cross files)."""
    pkg_parts = module_name.split(".")
    is_pkg = rel_path.endswith("__init__.py")
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                # from .sibling import X — resolve against our package
                up = node.level - (1 if is_pkg else 0)
                anchor = pkg_parts[:len(pkg_parts) - up] if up else pkg_parts
                base = ".".join(anchor + ([node.module] if node.module else []))
            for alias in node.names:
                full = f"{base}.{alias.name}" if base else alias.name
                out[alias.asname or alias.name] = full
    return out


# ----------------------------------------------------------- body scanning

class _BodyScanner:
    """One pass over a function/method body: records self-attribute
    accesses with the lock set held at each site, call sites, and thread
    seeds.  Locks are tracked through ``with self._lock:`` (multi-item,
    nested) and simple local aliases (``lk = self._lock; with lk:``);
    bare ``.acquire()`` is deliberately NOT modelled — a conditional
    acquire makes the held set path-dependent, and guessing would turn
    missed races into false confidence."""

    def __init__(self, fn: FunctionInfo):
        self.fn = fn
        self.cls = fn.cls
        self.module = fn.module
        # guarded-by: none on all scanner state: instances are per-body,
        # single-threaded; labels reaching here are unique-name
        # over-approximation (something thread-labelled calls a .scan())
        self.locks: List[str] = []          # guarded-by: none (per-body scanner) — held-lock stack
        self.lock_aliases: Dict[str, str] = {}  # guarded-by: none (per-body scanner)
        self.local_types: Dict[str, str] = {}   # guarded-by: none (per-body scanner) — var → class qualname
        #: nested `def run(): ...` names — a Thread(target=run) seed on a
        #: local closure labels THIS body (its accesses were scanned here)
        self.nested_defs: Set[str] = set()  # guarded-by: none (per-body scanner)

    # -- entry ----------------------------------------------------------
    def scan(self):
        node = self.fn.node
        self._collect_param_types(node)
        self._stmts(node.body)

    def _collect_param_types(self, node):
        for arg in list(node.args.posonlyargs) + list(node.args.args):
            t = self._annotation_type(arg.annotation)
            if t:
                self.local_types[arg.arg] = t

    def _annotation_type(self, ann) -> Optional[str]:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            # string annotation: 'OpsServer' / "gateway.ServingGateway"
            name = ann.value.strip().strip('"\'')
            return self._dotted_to_class(name)
        resolved = self.module.resolve_name(ann)
        return self._dotted_to_class(resolved) if resolved else None

    def _dotted_to_class(self, dotted: str) -> Optional[str]:
        # Resolution against the finished program happens later; store the
        # dotted guess, Program.resolve_calls maps it to a ClassInfo.
        return self.module.imports.get(dotted, dotted)

    # -- statements ------------------------------------------------------
    def _stmts(self, body: Sequence[ast.stmt]):
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt):
        if isinstance(stmt, ast.With):
            pushed = 0
            for item in stmt.items:
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    self.locks.append(lock)
                    pushed += 1
                else:
                    self._expr(item.context_expr)
            self._stmts(stmt.body)
            for _ in range(pushed):
                self.locks.pop()
        elif isinstance(stmt, ast.Assign):
            self._expr(stmt.value)
            self._track_assign(stmt)
            for t in stmt.targets:
                self._target(t)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value)
            self._track_annassign(stmt)
            self._target(stmt.target)
        elif isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value)
            attr = self._self_attr(stmt.target)
            if attr:
                self._record(attr, MUTATE, stmt)
            else:
                self._target(stmt.target)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Subscript):
                    attr = self._self_attr(t.value)
                    if attr:
                        self._record(attr, MUTATE, t)
                        self._expr(t.slice)
                        continue
                attr = self._self_attr(t)
                if attr:
                    self._record(attr, WRITE, t)
                else:
                    self._expr(t)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            attr = self._iterable_attr(stmt.iter)
            if attr:
                self._record(attr, ITERATE, stmt.iter)
            else:
                self._expr(stmt.iter)
            self._target(stmt.target)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self._expr(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.If):
            self._expr(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body)
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self._expr(stmt.value)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: its body runs when called — commonly a thread
            # target; scan it as part of this fn (accesses attributed
            # here, which is where the closure's locks visibly aren't)
            self.nested_defs.add(stmt.name)
            held, self.locks = self.locks, []   # defs run without our locks
            self._stmts(stmt.body)
            self.locks = held
        elif isinstance(stmt, ast.ClassDef):
            pass
        elif isinstance(stmt, (ast.Raise, ast.Assert)):
            for v in (getattr(stmt, "exc", None), getattr(stmt, "cause", None),
                      getattr(stmt, "test", None), getattr(stmt, "msg", None)):
                if v is not None:
                    self._expr(v)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child)
                elif isinstance(child, ast.stmt):
                    self._stmt(child)

    # -- assignment bookkeeping -----------------------------------------
    def _track_assign(self, stmt: ast.Assign):
        if len(stmt.targets) != 1:
            return
        t = stmt.targets[0]
        if isinstance(t, ast.Attribute):
            dotted = self.module.resolve_name(t)
            if dotted in ("sys.excepthook", "threading.excepthook"):
                self._seed_from("signal-handler", stmt.value, stmt.lineno)
        if isinstance(t, ast.Name):
            # lock alias: lk = self._lock
            src = self._self_attr(stmt.value)
            if src and self._is_lock_name(src):
                self.lock_aliases[t.id] = src
            # local type: x = Cls(...)
            qual = self._ctor_type(stmt.value)
            if qual:
                self.local_types[t.id] = qual
        attr = self._self_attr(t)
        if attr and self.cls is not None:
            qual = self._ctor_type(stmt.value)
            if qual:
                self.cls.attr_types.setdefault(attr, qual)
            if self._is_lock_ctor(stmt.value) or _LOCKISH.search(attr):
                self.cls.lock_attrs.add(attr)

    def _track_annassign(self, stmt: ast.AnnAssign):
        t = stmt.target
        qual = self._annotation_type(stmt.annotation)
        if isinstance(t, ast.Name):
            if qual:
                self.local_types[t.id] = qual
        attr = self._self_attr(t)
        if attr and self.cls is not None:
            if qual:
                self.cls.attr_types.setdefault(attr, qual)
            if (stmt.value is not None and self._is_lock_ctor(stmt.value)) \
                    or _LOCKISH.search(attr):
                self.cls.lock_attrs.add(attr)

    def _is_lock_ctor(self, node) -> bool:
        return (isinstance(node, ast.Call)
                and (self.module.resolve_name(node.func) or "") in _LOCK_CTORS)

    def _ctor_type(self, node) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        name = self.module.resolve_name(node.func)
        if name and name[0].isupper() or (name and "." in name
                                          and name.rsplit(".", 1)[1][:1].isupper()):
            return name
        return None

    # -- targets (stores) ------------------------------------------------
    def _target(self, t: ast.expr):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)
            return
        if isinstance(t, ast.Subscript):
            attr = self._self_attr(t.value)
            if attr:
                self._record(attr, MUTATE, t)
            else:
                self._expr(t.value)
            self._expr(t.slice)
            return
        attr = self._self_attr(t)
        if attr:
            self._record(attr, WRITE, t)
        elif isinstance(t, ast.Attribute):
            self._expr(t.value)

    # -- expressions -----------------------------------------------------
    def _expr(self, node: ast.expr):
        if node is None:
            return
        if isinstance(node, ast.Call):
            self._call(node)
            return
        if isinstance(node, ast.Attribute):
            attr = self._self_attr(node)
            if attr:
                self._record(attr, READ, node)
                return
            self._expr(node.value)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                attr = self._iterable_attr(gen.iter)
                if attr:
                    self._record(attr, ITERATE, gen.iter)
                else:
                    self._expr(gen.iter)
                for cond in gen.ifs:
                    self._expr(cond)
            for part in ([node.key, node.value] if isinstance(node, ast.DictComp)
                         else [node.elt]):
                self._expr(part)
            return
        if isinstance(node, ast.Lambda):
            self._expr(node.body)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _call(self, node: ast.Call):
        func = node.func
        resolved = self.module.resolve_name(func)
        # ---- thread seeds ------------------------------------------------
        self._maybe_seed(node, resolved)
        # ---- iterating builtins over a self attr -------------------------
        if isinstance(func, ast.Name) and func.id in _ITERATING_BUILTINS \
                and node.args:
            attr = self._self_attr(node.args[0]) \
                or self._iterable_attr(node.args[0])
            if attr:
                self._record(attr, ITERATE, node)
                for a in node.args[1:]:
                    self._expr(a)
                for kw in node.keywords:
                    self._expr(kw.value)
                return
        # ---- method call on a self attribute -----------------------------
        if isinstance(func, ast.Attribute):
            recv_attr = self._self_attr(func.value)
            if recv_attr:
                if func.attr in _MUTATOR_METHODS:
                    self._record(recv_attr, MUTATE, node)
                elif func.attr in _ITERATOR_METHODS:
                    self._record(recv_attr, ITERATE, node)
                else:
                    self._record(recv_attr, READ, node)
                # typed attr → call edge into that class
                if self.cls is not None:
                    qual = self.cls.attr_types.get(recv_attr)
                    if qual:
                        self.fn.calls.append(CallSite(
                            "typed", func.attr, qual, node.lineno,
                            locks=frozenset(self.locks)))
                    else:
                        self.fn.calls.append(CallSite(
                            "unique", func.attr, "", node.lineno,
                            locks=frozenset(self.locks)))
            elif isinstance(func.value, ast.Name) and func.value.id == "self":
                self.fn.calls.append(CallSite("self", func.attr,
                                              line=node.lineno,
                                              locks=frozenset(self.locks)))
            else:
                # x.m() — typed local, else unique-name fallback
                base = func.value
                if isinstance(base, ast.Name) \
                        and base.id in self.local_types:
                    self.fn.calls.append(CallSite(
                        "typed", func.attr, self.local_types[base.id],
                        node.lineno, locks=frozenset(self.locks)))
                else:
                    self.fn.calls.append(CallSite("unique", func.attr, "",
                                                  node.lineno,
                                                  locks=frozenset(self.locks)))
                self._expr(func.value)
        elif resolved:
            self.fn.calls.append(CallSite("name", resolved.rsplit(".", 1)[-1],
                                          resolved, node.lineno,
                                          locks=frozenset(self.locks)))
        else:
            self._expr(func)
        for a in node.args:
            self._expr(a)
        for kw in node.keywords:
            self._expr(kw.value)

    # -- seeds -----------------------------------------------------------
    def _maybe_seed(self, node: ast.Call, resolved: Optional[str]):
        label = None
        target_expr = None
        name = resolved or ""
        meth = node.func.attr if isinstance(node.func, ast.Attribute) else ""
        if name in ("threading.Thread", "Thread") or name.endswith(".Thread"):
            label = "thread-target"
            target_expr = self._kwarg(node, "target")
        elif name in ("threading.Timer", "Timer"):
            label = "thread-target"
            target_expr = node.args[1] if len(node.args) > 1 \
                else self._kwarg(node, "function")
        elif meth == "submit" and (node.args or node.keywords):
            label = "pool-task"
            target_expr = node.args[0] if node.args else None
        elif meth == "subscribe" and node.args:
            label = "subscriber"
            target_expr = node.args[0]
        elif name == "signal.signal" and len(node.args) > 1:
            label = "signal-handler"
            target_expr = node.args[1]
        elif name in ("faulthandler.register",) and len(node.args) > 1:
            label = "signal-handler"
            target_expr = node.args[1]
        elif meth in ("map",) and isinstance(node.func, ast.Attribute) \
                and "executor" in ast.dump(node.func.value).lower():
            label = "pool-task"
            target_expr = node.args[0] if node.args else None
        if label is None or target_expr is None:
            return
        self._seed_from(label, target_expr, node.lineno)

    def _seed_from(self, label: str, target_expr: ast.expr, lineno: int):
        # a target that is a nested def of THIS body labels this body
        # directly — its statements were scanned into fn.accesses
        if isinstance(target_expr, ast.Name) \
                and target_expr.id in self.nested_defs:
            self.fn.thread_labels.add(label)
            self.fn.seeds.append(Seed(
                label, CallSite("name", target_expr.id,
                                self.fn.qualname, lineno), lineno))
            return
        site = self._callable_ref(target_expr)
        if site is not None:
            self.fn.seeds.append(Seed(label, site, lineno))

    def _callable_ref(self, expr: ast.expr) -> Optional[CallSite]:
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                return CallSite("self", expr.attr, line=expr.lineno)
            base = expr.value
            if isinstance(base, ast.Name) and base.id in self.local_types:
                return CallSite("typed", expr.attr,
                                self.local_types[base.id], expr.lineno)
            return CallSite("unique", expr.attr, "", expr.lineno)
        if isinstance(expr, ast.Name):
            dotted = self.module.imports.get(expr.id, None)
            if dotted:
                return CallSite("name", dotted.rsplit(".", 1)[-1], dotted,
                                expr.lineno)
            return CallSite("name", expr.id,
                            f"{self.module.name}.{expr.id}", expr.lineno)
        if isinstance(expr, ast.Lambda):
            # seed every call the lambda body makes
            body_calls: List[CallSite] = []
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Call):
                    ref = self._callable_ref(sub.func)
                    if ref is not None:
                        body_calls.append(ref)
            return body_calls[0] if body_calls else None
        return None

    @staticmethod
    def _kwarg(node: ast.Call, name: str) -> Optional[ast.expr]:
        for kw in node.keywords:
            if kw.arg == name:
                return kw.value
        return None

    # -- attr/lock helpers -----------------------------------------------
    @staticmethod
    def _self_attr(node) -> Optional[str]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    def _iterable_attr(self, node) -> Optional[str]:
        """self._x, self._x.items()/keys()/values(), or alias thereof —
        the receiver attr being walked."""
        attr = self._self_attr(node)
        if attr:
            return attr
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _ITERATOR_METHODS:
            return self._self_attr(node.func.value)
        return None

    def _is_lock_name(self, attr: str) -> bool:
        if self.cls is not None and attr in self.cls.lock_attrs:
            return True
        return bool(_LOCKISH.search(attr))

    def _lock_of(self, expr) -> Optional[str]:
        attr = self._self_attr(expr)
        if attr and self._is_lock_name(attr):
            return attr
        if isinstance(expr, ast.Name) and expr.id in self.lock_aliases:
            return self.lock_aliases[expr.id]
        return None

    def _record(self, attr: str, kind: str, node):
        self.fn.accesses.append(Access(
            attr=attr, kind=kind, locks=frozenset(self.locks),
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1))


# --------------------------------------------------------------- program

class Program:
    """The whole-program model: build once, query from the passes."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        #: method name → every FunctionInfo defining it (unique-name edges)
        self.methods_by_name: Dict[str, List[FunctionInfo]] = {}
        self.seeds: List[Tuple[FunctionInfo, Seed]] = []
        self.skipped: List[str] = []     # unparseable files (reported per-file)
        #: memoized resolution — propagate() and inherited_locks() both
        #: walk every call edge repeatedly; suffix-matching classes per
        #: visit would be quadratic in tree size
        self._dotted_cache: Dict[str, Optional[ClassInfo]] = {}
        self._edge_cache: Dict[int, List[Tuple[CallSite, List[FunctionInfo]]]] = {}

    # -- construction ----------------------------------------------------
    @classmethod
    def build(cls, paths: Sequence[Path], root: Path) -> "Program":
        prog = cls(root)
        for f, rel in iter_py_files(paths, root):
            try:
                source = f.read_text(encoding="utf-8")
                tree = ast.parse(source, filename=rel)
            except (UnicodeDecodeError, SyntaxError):
                prog.skipped.append(rel)   # per-file stage already reports it
                continue
            name = _module_name(rel)
            prog.modules[name] = ModuleInfo(name, rel, source, tree)
        for mod in prog.modules.values():
            prog._scan_module(mod)
        prog._finish()
        return prog

    def _scan_module(self, mod: ModuleInfo):
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                ci = ClassInfo(mod, node)
                ci.base_names = [b for b in
                                 (mod.resolve_name(base) for base in node.bases)
                                 if b]
                mod.classes[ci.name] = ci
                self.classes[ci.qualname] = ci
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fi = FunctionInfo(mod, sub, ci)
                        ci.methods[fi.name] = fi
                        self.functions[fi.qualname] = fi
                        self.methods_by_name.setdefault(fi.name, []).append(fi)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FunctionInfo(mod, node)
                mod.functions[fi.name] = fi
                self.functions[fi.qualname] = fi
                self.methods_by_name.setdefault(fi.name, []).append(fi)
        for fi in list(mod.functions.values()):
            _BodyScanner(fi).scan()
        for ci in mod.classes.values():
            for fi in ci.methods.values():
                _BodyScanner(fi).scan()
            self._scan_guarded_by(ci)
            self._scan_init_order(ci)

    def _scan_guarded_by(self, ci: ClassInfo):
        """# guarded-by: annotations inside the class body (usually
        __init__): trailing on the attr's assignment line, or — mirroring
        the pragma convention — on comment-only line(s) directly above it
        (long reasons don't fit a trailing comment)."""
        start = ci.node.lineno
        end = max((getattr(n, "end_lineno", start) or start
                   for n in ast.walk(ci.node)), default=start)
        end = min(end, len(ci.module.lines))
        for lineno in range(start, end + 1):
            text = ci.module.lines[lineno - 1]
            m = GUARDED_BY_RE.search(text)
            if not m:
                continue
            am = _SELF_ATTR_ASSIGN_RE.search(text)
            target_line = lineno
            if am is None and text.lstrip().startswith("#"):
                # comment-only annotation: covers the next code line,
                # skipping further comment-only lines
                nxt = lineno + 1
                while nxt <= end and ci.module.lines[nxt - 1].lstrip() \
                        .startswith("#"):
                    nxt += 1
                if nxt <= end:
                    am = _SELF_ATTR_ASSIGN_RE.search(ci.module.lines[nxt - 1])
                    target_line = nxt
            if not am:
                continue
            ci.guarded_by[am.group(1)] = (m.group(1), target_line)

    def _scan_init_order(self, ci: ClassInfo):
        init = ci.methods.get("__init__")
        if init is None:
            return
        for a in init.accesses:
            if a.kind == WRITE and a.attr not in ci.init_assign_line:
                ci.init_assign_line[a.attr] = a.line
        for seed in init.seeds:
            ci.init_publishes.append((seed.line, seed))
        # a Thread assigned in __init__ and .start()ed later in __init__:
        # the seed is recorded at Thread(...); treat its line as publish.

    def _finish(self):
        # link base classes
        for ci in self.classes.values():
            for b in ci.base_names:
                target = self._class_by_dotted(b)
                if target is not None:
                    ci.bases.append(target)
        # collect seeds: explicit ones + http-handler classes
        for fi in self.functions.values():
            for seed in fi.seeds:
                self.seeds.append((fi, seed))
        for ci in self.classes.values():
            if ci.is_http_handler():
                for m in ci.methods.values():
                    m.thread_labels.add("http-handler")

    # -- resolution ------------------------------------------------------
    def _class_by_dotted(self, dotted: str) -> Optional[ClassInfo]:
        if dotted in self._dotted_cache:
            return self._dotted_cache[dotted]
        out = self._class_by_dotted_uncached(dotted)
        self._dotted_cache[dotted] = out
        return out

    def _class_by_dotted_uncached(self, dotted: str) -> Optional[ClassInfo]:
        if dotted in self.classes:
            return self.classes[dotted]
        # suffix match: imports may resolve to a shorter path than the
        # file-derived module name (e.g. "gateway.ServingGateway" vs
        # "paddle_tpu.gateway.ServingGateway")
        tail = dotted.rsplit(".", 1)
        if len(tail) == 2:
            mod_tail, cls_name = tail
            hits = [c for q, c in self.classes.items()
                    if q.endswith(f"{mod_tail}.{cls_name}")
                    or (q.split(".")[-1] == cls_name
                        and q.split(".")[-2] == mod_tail.split(".")[-1])]
            if len(hits) == 1:
                return hits[0]
        hits = [c for c in self.classes.values() if c.name == dotted]
        if len(hits) == 1:
            return hits[0]
        return None

    def resolve_call(self, fn: FunctionInfo,
                     site: CallSite) -> List[FunctionInfo]:
        if site.shape == "self" and fn.cls is not None:
            m = fn.cls.method(site.name)
            return [m] if m is not None else []
        if site.shape == "typed":
            ci = self._class_by_dotted(site.qualifier)
            if ci is not None:
                m = ci.method(site.name)
                if m is not None:
                    return [m]
            return self._unique(site.name)
        if site.shape == "name":
            dotted = site.qualifier
            # module-level function?
            if dotted in self.functions:
                return [self.functions[dotted]]
            mod_name, _, tail = dotted.rpartition(".")
            mod = self.modules.get(mod_name)
            if mod is not None and tail in mod.functions:
                return [mod.functions[tail]]
            # constructor → __init__
            ci = self._class_by_dotted(dotted)
            if ci is not None:
                init = ci.method("__init__")
                return [init] if init is not None else []
            # suffix match on function qualnames
            hits = [f for q, f in self.functions.items()
                    if q.endswith("." + dotted.rsplit(".", 1)[-1])
                    and f.cls is None]
            if len(hits) == 1:
                return hits
            return []
        if site.shape == "unique":
            return self._unique(site.name)
        return []

    def _unique(self, name: str) -> List[FunctionInfo]:
        if name in _NEVER_UNIQUE or name.startswith("__"):
            return []
        hits = self.methods_by_name.get(name, [])
        return hits if len(hits) == 1 else []

    def resolved_calls(self, fn: FunctionInfo,
                       ) -> List[Tuple[CallSite, List[FunctionInfo]]]:
        """fn's call sites with resolved targets, memoized — both fixpoint
        walks (labels, inherited locks) revisit every edge per iteration."""
        key = id(fn)
        cached = self._edge_cache.get(key)
        if cached is None:
            cached = [(site, self.resolve_call(fn, site))
                      for site in fn.calls]
            self._edge_cache[key] = cached
        return cached

    # -- inherited locks -------------------------------------------------
    def entry_points(self) -> Set[str]:
        """Qualnames callable from OUTSIDE the modelled call graph with no
        locks held: direct seed targets plus every http-handler method."""
        out: Set[str] = set()
        for fn, seed in self.seeds:
            for t in self.resolve_call(fn, seed.target):
                out.add(t.qualname)
        for ci in self.classes.values():
            if ci.is_http_handler():
                out.update(m.qualname for m in ci.methods.values())
        return out

    def inherited_locks(self) -> Dict[str, frozenset]:
        """Locks provably held on ENTRY to each body: the intersection,
        over every resolved call site, of the locks held at the site plus
        the caller's own inherited set (fixpoint).  Externally callable
        bodies — public methods, dunders, module-level functions, direct
        thread seeds, http-handler methods — start at ∅, since anyone can
        call them bare.  This is what keeps the caller-holds-the-lock
        helper convention (``emit() { with self._lock: self._append() }``,
        the ``*_locked`` suffix family) from reading as unlocked access:
        a private method ONLY ever called under ``self._lock`` inherits
        it.  Private methods with no resolved caller at all resolve to ∅
        too — dead code gets flagged rather than silently trusted."""
        TOP = None                     # lattice top: unconstrained (no caller seen)
        entries = self.entry_points()
        inh: Dict[str, Optional[frozenset]] = {}
        for q, fi in self.functions.items():
            if (fi.cls is None or not fi.name.startswith("_")
                    or fi.name.startswith("__") or q in entries):
                inh[q] = frozenset()
            else:
                inh[q] = TOP
        changed = True
        while changed:
            changed = False
            for q, fi in self.functions.items():
                base = inh[q]
                if base is TOP:
                    continue           # caller itself unconstrained: no info yet
                for site, targets in self.resolved_calls(fi):
                    for target in targets:
                        tq = target.qualname
                        # lock names are class-scoped attrs: a cross-class
                        # call can't carry the CALLER's lock names into the
                        # callee — its contribution is ∅ (correctly meets
                        # the target down to "no lock assumed").  self.m()
                        # is always same-object, even when resolution lands
                        # in a base class.
                        same_cls = site.shape == "self" or (
                            fi.cls is not None and target.cls is fi.cls)
                        contribution = (site.locks | base) if same_cls \
                            else frozenset()
                        cur = inh[tq]
                        new = contribution if cur is TOP else (cur & contribution)
                        if new != cur:
                            inh[tq] = new
                            changed = True
        return {q: (v if v is not None else frozenset())
                for q, v in inh.items()}

    # -- reachability ----------------------------------------------------
    def propagate(self) -> Dict[str, Set[str]]:
        """Flow thread labels from seeds through the call graph.  Returns
        {method qualname → labels} for every labelled body (http-handler
        classes are pre-labelled in _finish)."""
        work: List[Tuple[FunctionInfo, str]] = []
        for fn, seed in self.seeds:
            for target in self.resolve_call(fn, seed.target):
                work.append((target, seed.label))
        for fi in self.functions.values():
            for label in fi.thread_labels:
                work.append((fi, label))
        seen: Set[Tuple[str, str]] = set()
        while work:
            fn, label = work.pop()
            key = (fn.qualname, label)
            if key in seen:
                continue
            seen.add(key)
            fn.thread_labels.add(label)
            for _site, targets in self.resolved_calls(fn):
                for target in targets:
                    if (target.qualname, label) not in seen:
                        work.append((target, label))
        return {fi.qualname: set(fi.thread_labels)
                for fi in self.functions.values() if fi.thread_labels}

    # -- reporting -------------------------------------------------------
    def seed_table(self) -> List[Dict[str, object]]:
        rows = []
        for fn, seed in self.seeds:
            targets = [t.qualname for t in self.resolve_call(fn, seed.target)]
            rows.append({"label": seed.label, "in": fn.qualname,
                         "path": fn.module.rel_path, "line": seed.line,
                         "target": seed.target.name,
                         "resolved": sorted(targets)})
        for ci in self.classes.values():
            if ci.is_http_handler():
                rows.append({"label": "http-handler", "in": ci.qualname,
                             "path": ci.module.rel_path,
                             "line": ci.node.lineno,
                             "target": "*", "resolved":
                             sorted(m.qualname for m in ci.methods.values())})
        return sorted(rows, key=lambda r: (r["path"], r["line"]))


def _module_name(rel_path: str) -> str:
    parts = Path(rel_path).with_suffix("").parts
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else rel_path
