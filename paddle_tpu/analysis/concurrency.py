"""tpulint whole-program concurrency passes (``tools/tpulint.py --program``).

Three passes over the :mod:`.program` model, each grounded in a race this
tree has already shipped and hand-fixed:

1. **Thread-entry reachability** — seed from the concurrent entry points
   the repo actually has (``ThreadingHTTPServer`` route handlers,
   ``SLOMonitor.subscribe`` callbacks, ``threading.Thread``/pool
   ``submit`` targets, signal/excepthook paths) and flow the labels
   through the call graph.  The result feeds pass 2 and is exported as a
   seed table in the ``--program --json`` report.

2. **Guarded-by inference + race detection** — infer which ``self._*``
   attributes are guarded by which locks from ``with self._lock:`` blocks
   (aliases, nesting, multi-item ``with`` handled; bare ``.acquire()``
   deliberately not guessed), honor explicit ``# guarded-by: <lock>``
   annotations, then flag:

   - ``guarded-by-race`` — the attr has a guard (inferred from locked
     writes, or declared) but is touched without it on a path a second
     thread reaches: the exact post-PR-8 ``gateway._disagg`` shape before
     its lock landed;
   - ``unguarded-shared-state`` — the attr is container-mutated or
     iterated across thread classes with NO lock anywhere: the pre-PR-11
     ``autoscaler._firing`` set-churn shape;
   - ``publish-before-init`` — ``__init__`` hands ``self`` to another
     thread (Thread target / subscriber / pool task) BEFORE assigning an
     attribute that thread's entry path reads;
   - ``bad-guarded-by`` — a ``# guarded-by:`` annotation naming a lock
     the class never defines (meta: the annotation layer must not rot).

   Plain unlocked scalar rebinds/reads are deliberately NOT flagged —
   CPython makes single-reference publication effectively atomic, and
   flagging them would bury the iterate-while-mutated signal the pass
   exists for.  Findings ride the engine's pragma + ratchet-baseline
   machinery; ``# guarded-by: none`` on the init line declares an attr
   deliberately unguarded (say why in the trailing text).

3. The dynamic complement — the runtime lock-order/guard sanitizer —
   lives in :mod:`.lock_sanitizer`; its fixtures validate these static
   verdicts against the real threaded suites.

Stdlib-only, like the rest of the package: the full ``--program`` sweep
parses the tree once and never imports JAX.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import Finding, _pragmas
from .program import (ITERATE, MUTATE, READ, WRITE, Access, ClassInfo,
                      FunctionInfo, Program)

#: rule id → hazard line (mirrors engine.Rule.hazard; surfaced by
#: --list-rules and docs/STATIC_ANALYSIS.md)
PROGRAM_RULES: Dict[str, str] = {
    "guarded-by-race": (
        "an attribute written under a lock on one path is read/iterated "
        "without it on a path another thread reaches — a scrape thread can "
        "observe a torn tick (the gateway._disagg shape)"),
    "unguarded-shared-state": (
        "a container attribute is mutated and iterated across thread "
        "classes with no lock anywhere — set/dict churn from a callback "
        "thread tears iteration on the main path (the autoscaler._firing "
        "shape)"),
    "publish-before-init": (
        "__init__ hands self to another thread (Thread target, subscriber, "
        "pool task) before assigning an attribute that thread reads — the "
        "new thread can observe the half-constructed object"),
    "bad-guarded-by": (
        "a # guarded-by: annotation names a lock the class never defines — "
        "the declared discipline can't be checked and will rot"),
}

#: attrs never analyzed: locks themselves, thread-locals, and the
#: back-reference shapes that are written once and read structurally
_SKIP_ATTRS = ("_tls",)


@dataclasses.dataclass
class ProgramReport:
    """Machine-readable side-channel of a --program run (JSON output)."""

    seed_table: List[Dict[str, object]]
    shared_methods: Dict[str, List[str]]   # qualname → sorted labels
    guarded_attrs: List[Dict[str, object]]

    def to_dict(self) -> Dict[str, object]:
        return {"thread_entries": self.seed_table,
                "shared_methods": {k: sorted(v)
                                   for k, v in sorted(self.shared_methods.items())},
                "guarded_attrs": self.guarded_attrs}


def analyze_program(paths: Sequence[Path], root: Path,
                    ) -> Tuple[List[Finding], ProgramReport]:
    """Build the program model over ``paths`` and run all passes.
    Returns (pragma-filtered findings, report)."""
    prog = Program.build(paths, root)
    shared = prog.propagate()
    inherited = prog.inherited_locks()
    findings: List[Finding] = []
    guarded_rows: List[Dict[str, object]] = []
    for ci in prog.classes.values():
        findings.extend(_race_pass(ci, guarded_rows, inherited))
        findings.extend(_publish_pass(ci))
        findings.extend(_annotation_pass(ci))
    findings = _apply_pragmas(prog, findings)
    report = ProgramReport(seed_table=prog.seed_table(),
                           shared_methods=shared,
                           guarded_attrs=guarded_rows)
    return sorted(findings), report


# ------------------------------------------------------------- race pass

def _race_pass(ci: ClassInfo, guarded_rows: List[Dict[str, object]],
               inherited: Dict[str, frozenset]) -> Iterable[Finding]:
    by_attr: Dict[str, List[Tuple[FunctionInfo, Access]]] = {}
    for fn, a in ci.all_accesses():
        if a.attr in ci.lock_attrs or a.attr in _SKIP_ATTRS \
                or a.attr.startswith("__"):
            continue
        by_attr.setdefault(a.attr, []).append((fn, a))

    def eff(fn: FunctionInfo, a: Access) -> frozenset:
        # locks visibly held at the site, plus locks provably held on
        # entry to the method (private helper called only under a lock)
        return a.locks | inherited.get(fn.qualname, frozenset())

    out: List[Finding] = []
    for attr, sites in sorted(by_attr.items()):
        declared = ci.guard_declaration(attr)
        if declared is not None and declared[0] == "none":
            continue          # deliberately unguarded, annotated as such

        non_init = [(fn, a) for fn, a in sites if fn.name != "__init__"]
        writes = [(fn, a) for fn, a in non_init
                  if a.kind in (WRITE, MUTATE)]
        if not writes:
            continue          # immutable after construction: no race

        # guard inference: the lock most often held at a write/mutate
        locked_writes = [(fn, a) for fn, a in writes if eff(fn, a)]
        guard: Optional[str] = None
        source = ""
        if declared is not None:
            guard, source = declared[0], "declared"
        elif locked_writes:
            tally: Dict[str, int] = {}
            for fn, a in locked_writes:
                for lk in eff(fn, a):
                    tally[lk] = tally.get(lk, 0) + 1
            guard = max(sorted(tally), key=lambda k: tally[k])
            source = f"inferred from {tally[guard]} locked write(s)"

        shared_fns = [fn for fn, _ in sites if fn.thread_labels]
        if not shared_fns:
            continue          # nothing else ever threads through this attr
        labels = sorted({lb for fn in shared_fns for lb in fn.thread_labels})

        if guard is not None:
            guarded_rows.append({
                "class": ci.qualname, "attr": attr, "lock": guard,
                "source": source, "threads": labels})
            for fn, a in non_init:
                if guard in eff(fn, a):
                    continue
                # unlocked plain reads only matter on the concurrent path;
                # unlocked writes/mutates/iterates race the locked side
                # from anywhere once a second thread is in the class
                if a.kind == READ and not fn.thread_labels:
                    continue
                out.append(Finding(
                    path=ci.module.rel_path, line=a.line, col=a.col,
                    rule="guarded-by-race",
                    message=(f"self.{attr} is guarded by self.{guard} "
                             f"({source}) but this {a.kind} in "
                             f"{fn.qualname} runs without it; threads "
                             f"reaching the attr: {', '.join(labels)}")))
        else:
            mutates = [(fn, a) for fn, a in non_init if a.kind == MUTATE]
            iterates = [(fn, a) for fn, a in non_init if a.kind == ITERATE]
            if not mutates:
                continue      # plain rebinds: atomic publication, allowed
            threaded_mutate = any(fn.thread_labels for fn, _ in mutates)
            if not (iterates or threaded_mutate):
                continue
            for fn, a in mutates + iterates:
                shape = ("iterated while mutated" if iterates
                         else "mutated from a second thread")
                out.append(Finding(
                    path=ci.module.rel_path, line=a.line, col=a.col,
                    rule="unguarded-shared-state",
                    message=(f"self.{attr} is {shape} with no lock anywhere "
                             f"(this {a.kind} in {fn.qualname}; threads "
                             f"reaching the attr: {', '.join(labels)}) — "
                             f"add a lock, or declare `# guarded-by: none` "
                             f"on its init line with the reason")))
    return out


# ---------------------------------------------------------- publish pass

def _publish_pass(ci: ClassInfo) -> Iterable[Finding]:
    if not ci.init_publishes:
        return []
    out: List[Finding] = []
    for publish_line, seed in sorted(ci.init_publishes):
        # attrs the published entry path reads: the seed's resolved target
        # methods (and, over-approximating, every thread-labelled method of
        # this class — the publish IS what creates the label)
        reached_attrs: Set[str] = set()
        target_names = {seed.target.name}
        for m in ci.methods.values():
            if m.name in target_names or m.thread_labels:
                reached_attrs.update(a.attr for a in m.accesses)
        for attr, line in sorted(ci.init_assign_line.items(),
                                 key=lambda kv: kv[1]):
            if line <= publish_line or attr not in reached_attrs:
                continue
            if attr in ci.lock_attrs:
                continue
            out.append(Finding(
                path=ci.module.rel_path, line=line, col=1,
                rule="publish-before-init",
                message=(f"self.{attr} is assigned after __init__ already "
                         f"published self to a {seed.label} at line "
                         f"{publish_line} ({seed.target.name}) — the new "
                         f"thread can read the attribute before it exists; "
                         f"assign state first, publish last")))
    return out


# ------------------------------------------------------- annotation pass

def _annotation_pass(ci: ClassInfo) -> Iterable[Finding]:
    out: List[Finding] = []
    for attr, (lock, line) in sorted(ci.guarded_by.items()):
        if lock == "none":
            continue
        if lock not in ci.all_lock_attrs():
            out.append(Finding(
                path=ci.module.rel_path, line=line, col=1,
                rule="bad-guarded-by",
                message=(f"# guarded-by: {lock} on self.{attr} names a lock "
                         f"{ci.name} never defines (known locks: "
                         f"{', '.join(sorted(ci.all_lock_attrs())) or 'none'})")))
    return out


# ------------------------------------------------------------ suppression

def _apply_pragmas(prog: Program, findings: List[Finding]) -> List[Finding]:
    """Program findings honor the same per-line ``# tpulint: disable=``
    pragmas as the per-file rules (bad-pragma findings are the per-file
    stage's job — not duplicated here)."""
    supp_by_path: Dict[str, Dict[int, set]] = {}
    for mod in prog.modules.values():
        supp, _bad = _pragmas(mod.source)
        supp_by_path[mod.rel_path] = supp
    out = []
    for f in findings:
        allowed = supp_by_path.get(f.path, {}).get(f.line, ())
        if f.rule in allowed or "all" in allowed:
            continue
        out.append(f)
    return out
