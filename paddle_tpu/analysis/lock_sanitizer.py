"""Lock-discipline sanitizer — the runtime complement to the static
``--program`` concurrency passes (:mod:`.concurrency`).

The static pass proves "this attr is guarded by that lock on every path
it can see"; this module checks the claim against what threads actually
do in the threaded test suites:

- :class:`LockSanitizer` wraps ``threading.Lock``/``RLock`` objects in
  recording proxies.  Every acquisition appends an edge (held → acquired)
  to a process-wide-per-sanitizer lock-order graph; an acquisition that
  closes a cycle is a **lock-order inversion** (the deadlock shape) and
  is recorded as a violation with both conflicting edges' call sites.
- :meth:`LockSanitizer.guard` wraps a container attribute (dict / set /
  list / deque) in a checking proxy that records a **guarded-by
  violation** whenever the declared lock is not held by the accessing
  thread at a read, iteration, or mutation.  Declarations can be wired
  by hand or harvested from the same ``# guarded-by: <lock>`` source
  annotations the static pass reads (:meth:`instrument_guards`), so the
  two layers can never drift.
- Violations are RECORDED, not raised, at the access site (raising inside
  an instrumented ``__iter__`` would turn a diagnosis into a new crash in
  someone else's thread); the pytest fixture asserts ``violations() ==
  []`` at teardown, so the test that provoked the race is the test that
  fails, with every site listed.

Opt-in and stdlib-only: nothing in the serving stack imports this; tests
construct a sanitizer, ``instrument()`` the objects under test, run the
threaded scenario, and the fixture fails on anything recorded.  See
``tests/conftest.py`` (``lock_sanitizer`` fixture) and
docs/STATIC_ANALYSIS.md § Lock-discipline sanitizer.
"""

from __future__ import annotations

import inspect
import re
import threading
import traceback
from typing import Any, Dict, List, Optional, Set, Tuple

from .program import GUARDED_BY_RE, _SELF_ATTR_ASSIGN_RE

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))


def _site(skip: int = 3) -> str:
    """Caller's file:line, skipping sanitizer frames — the violation
    message must point at the racing code, not at this module."""
    for frame in traceback.extract_stack()[-(skip + 6)::][::-1]:
        if "lock_sanitizer" not in frame.filename:
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


class _InstrumentedLock:
    """Recording proxy over a Lock/RLock: same acquire/release/context
    surface, plus owner tracking (which threads hold it now) feeding the
    sanitizer's order graph and guard checks."""

    def __init__(self, sanitizer: "LockSanitizer", inner, name: str):
        self._san = sanitizer
        self._inner = inner
        self.name = name
        self._owners: Dict[int, int] = {}        # thread ident → depth
        self._owners_guard = threading.Lock()

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._san._on_acquire(self)
            ident = threading.get_ident()
            with self._owners_guard:
                self._owners[ident] = self._owners.get(ident, 0) + 1
        return got

    def release(self):
        ident = threading.get_ident()
        with self._owners_guard:
            depth = self._owners.get(ident, 0)
            if depth <= 1:
                self._owners.pop(ident, None)
            else:
                self._owners[ident] = depth - 1
        self._san._on_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def held_by_current_thread(self) -> bool:
        with self._owners_guard:
            return self._owners.get(threading.get_ident(), 0) > 0

    def __repr__(self):
        return f"<sanitized {self.name} over {self._inner!r}>"


class _GuardedContainer:
    """Checking proxy over a container: every read/iterate/mutate records
    a violation unless the declared lock is held by the CURRENT thread.
    ``__class__`` is forwarded so ``isinstance`` checks in instrumented
    code keep passing."""

    _MUTATORS = {"add", "append", "appendleft", "clear", "discard",
                 "extend", "extendleft", "insert", "pop", "popitem",
                 "popleft", "remove", "setdefault", "update"}

    def __init__(self, sanitizer: "LockSanitizer", inner, attr: str,
                 lock: _InstrumentedLock):
        object.__setattr__(self, "_san", sanitizer)
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_attr", attr)
        object.__setattr__(self, "_lock", lock)

    def _check(self, op: str):
        if not self._lock.held_by_current_thread():
            self._san._record_guard_violation(self._attr, self._lock.name, op)

    # -- reads -----------------------------------------------------------
    def __iter__(self):
        self._check("iterate")
        return iter(self._inner)

    def __len__(self):
        self._check("len")
        return len(self._inner)

    def __contains__(self, item):
        self._check("contains")
        return item in self._inner

    def __getitem__(self, key):
        self._check("getitem")
        return self._inner[key]

    def __bool__(self):
        self._check("bool")
        return bool(self._inner)

    # -- mutations -------------------------------------------------------
    def __setitem__(self, key, value):
        self._check("setitem")
        self._inner[key] = value

    def __delitem__(self, key):
        self._check("delitem")
        del self._inner[key]

    def __getattr__(self, name):
        value = getattr(self._inner, name)
        if callable(value):
            op = "mutate" if name in self._MUTATORS else "read"

            def checked(*a, _value=value, _op=op, **kw):
                self._check(_op)
                return _value(*a, **kw)
            return checked
        return value

    @property
    def __class__(self):      # isinstance(proxy, dict/set/...) keeps working
        return type(self._inner)

    def __repr__(self):
        return f"<guarded {self._attr} by {self._lock.name}: {self._inner!r}>"


class LockSanitizer:
    """Opt-in runtime recorder of lock-order inversions and guarded-by
    violations.  One sanitizer per test; ``assert_clean()`` at teardown."""

    def __init__(self, name: str = "sanitizer"):
        self.name = name
        self._graph_lock = threading.Lock()
        #: (a, b) → first acquisition site proving a-held-then-b
        self._edges: Dict[Tuple[str, str], str] = {}
        self._held = threading.local()
        self._violations: List[Dict[str, Any]] = []
        self._wrapped: List[_InstrumentedLock] = []

    # -- wrapping --------------------------------------------------------
    def wrap(self, lock, name: str) -> _InstrumentedLock:
        if isinstance(lock, _InstrumentedLock):
            return lock
        w = _InstrumentedLock(self, lock, name)
        self._wrapped.append(w)
        return w

    def instrument(self, obj, names: Optional[List[str]] = None,
                   prefix: str = "") -> List[str]:
        """Replace every ``threading.Lock``/``RLock`` attribute on ``obj``
        (or just ``names``) with a recording proxy.  Returns the wrapped
        attribute names.  Idempotent."""
        wrapped: List[str] = []
        prefix = prefix or type(obj).__name__
        candidates = names if names is not None else [
            n for n, v in vars(obj).items() if isinstance(v, _LOCK_TYPES)]
        for n in candidates:
            v = getattr(obj, n, None)
            if isinstance(v, _InstrumentedLock):
                continue
            if not isinstance(v, _LOCK_TYPES):
                continue
            setattr(obj, n, self.wrap(v, f"{prefix}.{n}"))
            wrapped.append(n)
        return wrapped

    def guard(self, obj, attr: str, lock_attr: str) -> bool:
        """Wrap container ``obj.<attr>`` so every access checks that
        ``obj.<lock_attr>`` (instrumenting it first if needed) is held by
        the accessing thread.  Returns False when the attr isn't a
        wrappable container."""
        lock = getattr(obj, lock_attr, None)
        if not isinstance(lock, _InstrumentedLock):
            got = self.instrument(obj, names=[lock_attr])
            if not got:
                return False
            lock = getattr(obj, lock_attr)
        value = getattr(obj, attr, None)
        if isinstance(value, _GuardedContainer):
            return True
        if not isinstance(value, (dict, set, list)) \
                and not hasattr(value, "__iter__"):
            return False
        setattr(obj, attr, _GuardedContainer(
            self, value, f"{type(obj).__name__}.{attr}", lock))
        return True

    def instrument_guards(self, obj) -> List[Tuple[str, str]]:
        """Harvest ``# guarded-by: <lock>`` annotations from the object's
        class source (the SAME syntax the static pass reads) and wire a
        :meth:`guard` for each — statically-declared discipline becomes a
        runtime assertion with zero duplicate bookkeeping.  Returns the
        (attr, lock) pairs wired; ``guarded-by: none`` attrs are skipped."""
        try:
            src = inspect.getsource(type(obj))
        except (OSError, TypeError):
            return []
        wired: List[Tuple[str, str]] = []
        lines = src.splitlines()
        for i, line in enumerate(lines):
            m = GUARDED_BY_RE.search(line)
            if not m or m.group(1) == "none":
                continue
            am = _SELF_ATTR_ASSIGN_RE.search(line)
            if am is None and line.lstrip().startswith("#"):
                # comment-only annotation covers the next code line,
                # skipping further comment lines (same as the static scan)
                j = i + 1
                while j < len(lines) and lines[j].lstrip().startswith("#"):
                    j += 1
                if j < len(lines):
                    am = _SELF_ATTR_ASSIGN_RE.search(lines[j])
            if not am:
                continue
            attr, lock_attr = am.group(1), m.group(1)
            if self.guard(obj, attr, lock_attr):
                wired.append((attr, lock_attr))
        return wired

    # -- recording -------------------------------------------------------
    def _held_stack(self) -> List[str]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def _on_acquire(self, lock: _InstrumentedLock):
        stack = self._held_stack()
        if lock.name in stack:          # RLock re-entry: no new ordering
            stack.append(lock.name)
            return
        site = _site()
        with self._graph_lock:
            for held in set(stack):
                edge = (held, lock.name)
                if edge not in self._edges:
                    self._edges[edge] = site
                    cycle = self._find_cycle(lock.name, held)
                    if cycle:
                        self._violations.append({
                            "kind": "lock-order-inversion",
                            "thread": threading.current_thread().name,
                            "edge": f"{held} -> {lock.name}",
                            "site": site,
                            "conflicts_with": " -> ".join(cycle),
                            "conflict_sites": [
                                self._edges.get((a, b), "?")
                                for a, b in zip(cycle, cycle[1:])],
                        })
        stack.append(lock.name)

    def _find_cycle(self, start: str, goal: str) -> Optional[List[str]]:
        """Path start → … → goal through recorded edges = the reverse
        ordering that, combined with the edge just added, closes a cycle."""
        path = [start]
        seen: Set[str] = set()

        def dfs(node: str) -> bool:
            if node == goal:
                return True
            seen.add(node)
            for (a, b) in self._edges:
                if a == node and b not in seen:
                    path.append(b)
                    if dfs(b):
                        return True
                    path.pop()
            return False

        if dfs(start):
            return path + [start] if path[-1] != goal else path
        return None

    def _on_release(self, lock: _InstrumentedLock):
        stack = self._held_stack()
        if lock.name in stack:
            stack.reverse()
            stack.remove(lock.name)     # innermost occurrence
            stack.reverse()

    def _record_guard_violation(self, attr: str, lock_name: str, op: str):
        with self._graph_lock:
            self._violations.append({
                "kind": "guarded-by",
                "thread": threading.current_thread().name,
                "attr": attr, "lock": lock_name, "op": op,
                "site": _site(),
            })

    # -- results ---------------------------------------------------------
    def violations(self) -> List[Dict[str, Any]]:
        with self._graph_lock:
            return list(self._violations)

    def lock_order_edges(self) -> Dict[Tuple[str, str], str]:
        with self._graph_lock:
            return dict(self._edges)

    def assert_clean(self):
        vs = self.violations()
        if vs:
            lines = []
            for v in vs:
                if v["kind"] == "lock-order-inversion":
                    lines.append(
                        f"  lock-order inversion on {v['thread']}: "
                        f"{v['edge']} at {v['site']} conflicts with "
                        f"{v['conflicts_with']} "
                        f"(first seen at {', '.join(v['conflict_sites'])})")
                else:
                    lines.append(
                        f"  guarded-by violation on {v['thread']}: "
                        f"{v['op']} of {v['attr']} without {v['lock']} "
                        f"at {v['site']}")
            raise AssertionError(
                f"LockSanitizer({self.name}) recorded {len(vs)} "
                f"violation(s):\n" + "\n".join(lines))
