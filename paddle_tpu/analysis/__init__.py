"""tpulint — AST static analysis for JAX/TPU correctness hazards.

Stdlib-only on purpose: ``tools/tpulint.py`` loads this package by file
path (bypassing the JAX-importing ``paddle_tpu/__init__.py``) so a lint
sweep costs parse time, not framework import time.  Keep jax/numpy out of
this package.

Entry points: :func:`lint_paths` / :func:`lint_source` run the registered
per-file rules; ``RULES`` is the registry; ``PRINT_ALLOWLIST`` is the
frozen no-print inventory that tests/test_no_print.py wraps.  Baseline
ratchet helpers (``load_baseline`` / ``write_baseline`` /
``diff_baseline``) back the CI gate.  The whole-program concurrency
passes (``--program``: thread-entry reachability, guarded-by race
detection) live in :mod:`.concurrency` over the :mod:`.program` model;
their runtime complement — the lock-discipline test sanitizer — is
:mod:`.lock_sanitizer`.  See docs/STATIC_ANALYSIS.md.
"""

from .engine import (Finding, Rule, RULES, SCHEMA_VERSION, diff_baseline,  # noqa: F401
                     finding_counts, iter_py_files, lint_paths, lint_source,
                     load_baseline, register, render_json, render_text,
                     write_baseline)
from .rules import PRINT_ALLOWLIST  # noqa: F401
from .concurrency import PROGRAM_RULES, ProgramReport, analyze_program  # noqa: F401
from .lock_sanitizer import LockSanitizer  # noqa: F401
from .program import Program  # noqa: F401
