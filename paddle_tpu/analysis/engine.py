"""tpulint engine — AST static analysis for JAX/TPU correctness hazards.

JAX's worst failure modes are silent: a ``time.time()`` inside ``@jax.jit``
bakes one wall-clock value into the compiled program forever, a donated
buffer read after the call aliases freed device memory, an unseeded
``random.randint`` in distributed code desyncs replicas.  None of these
fail a unit test on CPU; all of them are visible in the AST.  This module
is the framework: rule registry, per-file visitor dispatch, pragma
suppression, ratchet-baseline diffing, and text/JSON rendering.  The rules
themselves live in :mod:`paddle_tpu.analysis.rules`.

Deliberately stdlib-only (``ast``/``re``/``json``/``pathlib``): the CLI
(``tools/tpulint.py``) loads this package by file path so a lint run never
pays a JAX import, and the whole sweep over ``paddle_tpu/`` + ``tools/``
stays well under the 20 s commit-hook budget.

Suppression: ``# tpulint: disable=<rule>(<reason>)`` on the offending line
— or on a comment line directly above it — silences that rule there.  The
reason is mandatory; a pragma without one is itself reported
(``bad-pragma``) and suppresses nothing, so "disable" can never be spelled
without an argument for the next reader.  ``disable=all(...)`` silences
every rule for the line.

Ratchet baseline: ``tools/tpulint_baseline.json`` freezes pre-existing
violation *counts* per (file, rule) — counts, not line numbers, so
unrelated edits don't churn it.  A count above baseline is a NEW violation
(exit 1); below baseline is STALE (exit 3) and the baseline must be
shrunk with ``--write-baseline`` — the ratchet only turns one way.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SCHEMA_VERSION = 1

PRAGMA_RE = re.compile(
    r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\-]+)\s*(?:\(([^)]*)\))?")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One violation.  ``path`` is repo-relative POSIX so baselines and
    JSON output are stable across checkouts."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


class Rule:
    """Base class.  Subclasses set ``name`` (kebab-case id) and ``hazard``
    (one-line consequence, surfaced in ``--list-rules`` and the docs) and
    implement ``check``."""

    name: str = ""
    hazard: str = ""
    #: substring precheck: when non-empty, the rule is skipped for files
    #: whose raw source contains none of these — pure optimization, so the
    #: hints MUST be implied by every finding the rule can produce
    hints: Tuple[str, ...] = ()

    def check(self, ctx: "FileContext") -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", node: ast.AST, message: str) -> Finding:
        return Finding(path=ctx.rel_path, line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       rule=self.name, message=message)


RULES: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate and enroll a rule.  Duplicate ids are a
    programming error, not a config surprise."""
    rule = rule_cls()
    if not rule.name:
        raise ValueError(f"{rule_cls.__name__} has no name")
    if rule.name in RULES:
        raise ValueError(f"duplicate rule id {rule.name!r}")
    RULES[rule.name] = rule
    return rule_cls


class FileContext:
    """Parsed view of one file handed to every rule: tree + raw lines +
    repo-relative path, plus the shared import map (local name → module
    fullname) so rules resolve ``np.random.randint`` without re-walking."""

    def __init__(self, rel_path: str, source: str, tree: ast.AST):
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.imports = _import_map(tree)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted fullname of a Name/Attribute chain with the first segment
        mapped through this file's imports; None for anything dynamic."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.imports.get(node.id, node.id)
        parts.append(head)
        return ".".join(reversed(parts))


def _import_map(tree: ast.AST) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for alias in node.names:
                out[alias.asname or alias.name] = f"{node.module}.{alias.name}"
    return out


# --------------------------------------------------------------- suppression

def _pragmas(source: str) -> Tuple[Dict[int, set], List[Finding]]:
    """Map line number → suppressed rule-id set.  A pragma covers its own
    line; on a comment-only line it also covers the next line (so multi-line
    statements can carry the pragma above the offending header).  Returns
    (suppressions, bad-pragma findings) — a reason is not optional.

    Scans actual COMMENT tokens, not raw lines: pragma syntax quoted in a
    docstring or string literal is documentation, never a live pragma (and
    never a bad-pragma finding)."""
    supp: Dict[int, set] = {}
    bad: List[Finding] = []
    lines = source.splitlines()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return supp, bad  # ast.parse already reported the file as broken
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = PRAGMA_RE.search(tok.string)
        if not m:
            continue
        row, col = tok.start
        names = {n.strip() for n in m.group(1).split(",") if n.strip()}
        reason = (m.group(2) or "").strip()
        if not reason:
            bad.append(Finding(path="", line=row, col=col + m.start() + 1,
                               rule="bad-pragma",
                               message="pragma without a (reason) — state why "
                                       "suppression is correct"))
            continue
        supp.setdefault(row, set()).update(names)
        comment_only = row <= len(lines) and not lines[row - 1][:col].strip()
        if comment_only:
            supp.setdefault(row + 1, set()).update(names)
    return supp, bad


def lint_source(rel_path: str, source: str,
                rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Lint one file's text.  Syntax errors are findings, not crashes — a
    file the linter can't parse can't be vouched for."""
    try:
        tree = ast.parse(source, filename=rel_path)
    except SyntaxError as e:
        return [Finding(path=rel_path, line=e.lineno or 1, col=(e.offset or 0) + 1,
                        rule="syntax-error", message=f"unparseable: {e.msg}")]
    ctx = FileContext(rel_path, source, tree)
    supp, bad = _pragmas(source)
    out: List[Finding] = [dataclasses.replace(f, path=rel_path) for f in bad]
    for rule in (rules if rules is not None else RULES.values()):
        if rule.hints and not any(h in source for h in rule.hints):
            continue
        for f in rule.check(ctx):
            allowed = supp.get(f.line, ())
            if f.rule in allowed or "all" in allowed:
                continue
            out.append(f)
    return sorted(out)


def iter_py_files(paths: Sequence[Path], root: Path) -> Iterable[Tuple[Path, str]]:
    seen: set = set()  # overlapping args (paddle_tpu + paddle_tpu/analysis)
    for p in paths:    # must not double-count against the ratchet
        p = Path(p)
        files = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in files:
            if "__pycache__" in f.parts or f.suffix != ".py":
                continue
            resolved = f.resolve()
            if resolved in seen:
                continue
            seen.add(resolved)
            try:
                rel = resolved.relative_to(root.resolve()).as_posix()
            except ValueError:
                rel = f.as_posix()
            yield f, rel


def lint_paths(paths: Sequence[Path], root: Path,
               rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    out: List[Finding] = []
    for f, rel in iter_py_files(paths, root):
        try:
            source = f.read_text(encoding="utf-8")  # py source is UTF-8 by spec
        except UnicodeDecodeError as e:
            out.append(Finding(path=rel, line=1, col=1, rule="syntax-error",
                               message=f"not valid UTF-8: {e.reason}"))
            continue
        out.extend(lint_source(rel, source, rules=rules))
    return sorted(out)


# ------------------------------------------------------------------ baseline

def finding_counts(findings: Iterable[Finding]) -> Dict[str, Dict[str, int]]:
    counts: Dict[str, Dict[str, int]] = {}
    for f in findings:
        counts.setdefault(f.path, {})
        counts[f.path][f.rule] = counts[f.path].get(f.rule, 0) + 1
    return counts


def load_baseline(path: Path) -> Dict[str, Dict[str, int]]:
    data = json.loads(Path(path).read_text())
    if data.get("version") != SCHEMA_VERSION:
        raise ValueError(f"baseline version {data.get('version')!r}, "
                         f"expected {SCHEMA_VERSION}")
    return data["counts"]


def write_baseline(path: Path, findings: Iterable[Finding],
                   paths: Optional[Sequence[str]] = None) -> None:
    """``paths`` records which lint roots the counts came from, so a later
    ``--write-baseline`` over a SUBSET can be refused instead of silently
    truncating the committed baseline."""
    payload = {
        "version": SCHEMA_VERSION,
        "note": ("Ratchet baseline: frozen pre-existing violation counts per "
                 "(file, rule). New violations fail CI; fixing one requires "
                 "shrinking this file via `python tools/tpulint.py "
                 "--write-baseline paddle_tpu tools`. Counts, not lines, so "
                 "unrelated edits don't churn it."),
        "counts": finding_counts(findings),
    }
    if paths is not None:
        payload["paths"] = sorted(str(p) for p in paths)
    Path(path).write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")


def diff_baseline(findings: Sequence[Finding],
                  baseline: Dict[str, Dict[str, int]],
                  active_rules: Optional[set] = None):
    """Returns (new, stale): ``new`` — findings in (file, rule) buckets whose
    count exceeds baseline (all sites listed, since the AST can't know which
    one was just added); ``stale`` — (path, rule, current, baselined) buckets
    the tree has burned below the frozen count.

    ``active_rules``: when given, baseline entries for rules OUTSIDE the
    set are ignored for the stale check — a run that skipped a stage
    (e.g. the per-file sweep without ``--program``) must not read that
    stage's frozen counts as burned-down violations."""
    current = finding_counts(findings)
    new: List[Finding] = []
    stale: List[Tuple[str, str, int, int]] = []
    for path, rules in sorted(current.items()):
        for rule, n in sorted(rules.items()):
            if n > baseline.get(path, {}).get(rule, 0):
                new.extend(f for f in findings
                           if f.path == path and f.rule == rule)
    for path, rules in sorted(baseline.items()):
        for rule, n in sorted(rules.items()):
            if active_rules is not None and rule not in active_rules:
                continue
            cur = current.get(path, {}).get(rule, 0)
            if cur < n:
                stale.append((path, rule, cur, n))
    return new, stale


# -------------------------------------------------------------------- output

def render_text(findings: Sequence[Finding]) -> str:
    return "\n".join(f.render() for f in findings)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps({
        "version": SCHEMA_VERSION,
        "findings": [dataclasses.asdict(f) for f in findings],
        "counts": finding_counts(findings),
    }, indent=2, sort_keys=True)
