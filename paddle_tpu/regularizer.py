"""Regularizers (reference: python/paddle/regularizer.py).

The reference appends a regularization op to each parameter's gradient
(L1DecayRegularizer → coeff·sign(p), L2DecayRegularizer → coeff·p; see
python/paddle/fluid/regularizer.py).  Here each regularizer contributes
``grad_term(p)`` which the optimizer adds to the gradient before the update
rule — the same coupled-decay semantics (decoupled AdamW-style decay
bypasses this path).
"""

from __future__ import annotations

import jax.numpy as jnp


class WeightDecayRegularizer:
    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    @property
    def _regularization_coeff(self):
        return self.coeff

    def grad_term(self, p):
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    """L1 decay: gradient contribution coeff * sign(p)."""

    def grad_term(self, p):
        return jnp.asarray(self.coeff, p.dtype) * jnp.sign(p)


class L2Decay(WeightDecayRegularizer):
    """L2 decay: gradient contribution coeff * p."""

    def grad_term(self, p):
        return jnp.asarray(self.coeff, p.dtype) * p
