"""Regularizers (reference: python/paddle/regularizer.py)."""

from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    @property
    def _regularization_coeff(self):
        return self.coeff


class L1Decay(WeightDecayRegularizer):
    pass


class L2Decay(WeightDecayRegularizer):
    pass
