"""Autograd public API (reference: python/paddle/autograd/__init__.py).

The eager engine itself lives in core/autograd.py (tape of Nodes replayed
via jax.vjp).  This package is the user-facing surface: multi-root
``backward``, ``PyLayer`` custom ops, and the functional transforms
(jacobian/hessian/vjp/jvp) which map 1:1 onto jax transforms over
functionalized callables — the reference builds these out of double-grad
graphs (python/paddle/autograd/functional.py); on TPU the native transforms
are both simpler and faster to compile.
"""

from __future__ import annotations

from ..core.autograd import (enable_grad, is_grad_enabled, no_grad,  # noqa: F401
                             set_grad_enabled)
from .py_layer import PyLayer, PyLayerContext  # noqa: F401

__all__ = ["backward", "PyLayer", "PyLayerContext", "no_grad", "enable_grad",
           "set_grad_enabled", "is_grad_enabled", "grad", "jacobian",
           "hessian", "vjp", "jvp"]


def grad(*args, **kwargs):
    from .. import grad as _grad
    return _grad(*args, **kwargs)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Multi-root backward (reference: autograd/backward_mode.py:23).

    Accumulates into leaf ``.grad`` for every root in ``tensors``.
    """
    from ..core import autograd as _engine
    from ..core.tensor import Tensor

    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    else:
        grad_tensors = (grad_tensors if isinstance(grad_tensors, (list, tuple))
                        else [grad_tensors])
    if len(grad_tensors) != len(tensors):
        raise ValueError(
            f"grad_tensors length ({len(grad_tensors)}) must match tensors "
            f"length ({len(tensors)})")
    if len({id(t) for t in tensors}) != len(tensors):
        raise RuntimeError("tensors in backward() must be unique")
    for i, (t, g) in enumerate(zip(tensors, grad_tensors)):
        # retain for all but the last root so shared subgraphs stay replayable
        keep = retain_graph or (i < len(tensors) - 1)
        _engine.backward(t, g, retain_graph=keep)


# ---------------------------------------------------------------------------
# Functional transforms over Tensor-callables
# ---------------------------------------------------------------------------

def _functionalize(func, n_in):
    """Wrap a Tensor-callable as a raw-array callable."""
    import jax
    from ..core.tensor import Tensor

    def raw(*datas):
        outs = func(*[Tensor(d) for d in datas])
        single = not isinstance(outs, (tuple, list))
        outs = (outs,) if single else tuple(outs)
        raws = tuple(getattr(o, "_data", o) for o in outs)
        return raws[0] if single else raws

    return raw


def _split_inputs(xs):
    xs = xs if isinstance(xs, (list, tuple)) else (xs,)
    return tuple(getattr(x, "_data", x) for x in xs)


def vjp(func, xs, v=None):
    """(outputs, input-cotangents) of ``func`` at ``xs`` (functional.py:vjp)."""
    import jax
    from ..core.tensor import Tensor

    datas = _split_inputs(xs)
    raw = _functionalize(func, len(datas))
    outs, vjp_fn = jax.vjp(raw, *datas)
    if v is None:
        import jax.numpy as jnp
        v = jax.tree_util.tree_map(jnp.ones_like, outs)
    else:
        v = jax.tree_util.tree_map(
            lambda t: getattr(t, "_data", t),
            v, is_leaf=lambda t: hasattr(t, "_data"))
    cots = vjp_fn(v)
    wrap = lambda tree: jax.tree_util.tree_map(Tensor, tree)
    return wrap(outs), wrap(cots if len(datas) > 1 else cots[0])


def jvp(func, xs, v=None):
    """(outputs, output-tangents) of ``func`` at ``xs``."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor

    datas = _split_inputs(xs)
    raw = _functionalize(func, len(datas))
    if v is None:
        tangents = tuple(jnp.ones_like(d) for d in datas)
    else:
        vs = v if isinstance(v, (list, tuple)) else (v,)
        tangents = tuple(getattr(t, "_data", t) for t in vs)
    outs, tangents_out = jax.jvp(raw, datas, tangents)
    wrap = lambda tree: jax.tree_util.tree_map(Tensor, tree)
    return wrap(outs), wrap(tangents_out)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """Jacobian of ``func`` at ``xs`` via ``jax.jacrev``."""
    import jax
    from ..core.tensor import Tensor

    datas = _split_inputs(xs)
    raw = _functionalize(func, len(datas))
    jac = jax.jacrev(raw, argnums=tuple(range(len(datas))))(*datas)
    wrapped = jax.tree_util.tree_map(Tensor, jac)
    if len(datas) == 1 and isinstance(wrapped, tuple) and len(wrapped) == 1:
        return wrapped[0]
    return wrapped


def hessian(func, xs, create_graph=False, allow_unused=False):
    """Hessian of a scalar-valued ``func`` at ``xs`` via ``jax.hessian``."""
    import jax
    from ..core.tensor import Tensor

    datas = _split_inputs(xs)
    raw = _functionalize(func, len(datas))
    hes = jax.hessian(raw, argnums=tuple(range(len(datas))))(*datas)
    wrapped = jax.tree_util.tree_map(Tensor, hes)
    if len(datas) == 1 and isinstance(wrapped, tuple) and len(wrapped) == 1:
        w = wrapped[0]
        return w[0] if isinstance(w, tuple) and len(w) == 1 else w
    return wrapped
