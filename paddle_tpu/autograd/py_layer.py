"""PyLayer: user-defined ops with custom backward
(reference: python/paddle/autograd/py_layer.py:21,133).

TPU-native wiring: the reference registers a C++ PyLayer grad node that
calls back into Python during backward (pylayer_op.cc).  Here the user's
``forward``/``backward`` pair becomes a ``jax.custom_vjp`` function that is
dispatched through the standard eager ``apply`` — so the op records one
tape Node like every built-in, replays correctly under ``jax.vjp``, works
inside jit (where forward/backward trace instead of running eagerly), and
composes with AMP/hooks for free.
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp


class PyLayerContext:
    """Carries state from forward to backward (reference py_layer.py:21)."""

    def __init__(self):
        self.container = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self.container = tensors

    def saved_tensor(self):
        return self.container


class PyLayer:
    """Subclass with ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    static methods; call via ``.apply(*args)``.

    ``backward`` receives one cotangent per forward output and must return
    one gradient per differentiable forward input (None → zero).
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError(
            "You must implement the forward function for PyLayer.")

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError(
            "You must implement the backward function for PyLayer.")

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.tensor import Tensor, apply as dispatch

        tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
        if not tensor_pos:
            ctx = PyLayerContext()
            return cls.forward(ctx, *args, **kwargs)
        # The eager call's ctx is kept for backward; re-traces (tape replay,
        # jit) run forward again with a throwaway ctx — recompute-not-save,
        # consistent with the tape's rebuild design.
        state = {"ctx": None, "n_out": None}

        def run_forward(ctx, datas):
            full = list(args)
            for p, d in zip(tensor_pos, datas):
                full[p] = Tensor(d, stop_gradient=True)
            outs = cls.forward(ctx, *full, **kwargs)
            single = not isinstance(outs, (tuple, list))
            outs = (outs,) if single else tuple(outs)
            state["n_out"] = len(outs)
            state["single"] = single
            return tuple(getattr(o, "_data", o) for o in outs)

        @jax.custom_vjp
        def op(*datas):
            ctx = PyLayerContext()
            if state["ctx"] is None:
                state["ctx"] = ctx
            return run_forward(ctx, datas)

        def op_fwd(*datas):
            ctx = PyLayerContext()
            if state["ctx"] is None:
                state["ctx"] = ctx
            return run_forward(ctx, datas), datas

        def op_bwd(res, gs):
            from ..core.autograd import no_grad
            from ..core.tensor import Tensor as T
            ctx = state["ctx"] if state["ctx"] is not None else PyLayerContext()
            with no_grad():
                grads = cls.backward(ctx, *[T(g, stop_gradient=True) for g in gs])
            single = not isinstance(grads, (tuple, list))
            grads = (grads,) if single else tuple(grads)
            # align with differentiable inputs; None → zeros
            raw: List[Any] = []
            gi = iter(grads)
            for d in res:
                try:
                    g = next(gi)
                except StopIteration:
                    g = None
                raw.append(jnp.zeros_like(d) if g is None
                           else getattr(g, "_data", g).astype(d.dtype).reshape(d.shape))
            return tuple(raw)

        op.defvjp(op_fwd, op_bwd)

        out = dispatch(op, *[args[i] for i in tensor_pos],
                       name=cls.__name__)
        if isinstance(out, tuple) and state.get("single", False):
            return out[0]
        if isinstance(out, tuple) and len(out) == 1:
            return out[0]
        return out
