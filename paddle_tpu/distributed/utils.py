"""Expert-parallel exchange utilities.

Reference: python/paddle/distributed/utils.py:57 ``global_scatter`` / :179
``global_gather`` — ragged token exchange driven by per-expert counts
(grouped ncclSend/Recv, operators/collective/global_scatter_op.cu.cc).

TPU-native: XLA collectives are static-shape, so the exchange is expressed as
a **uniform-capacity all_to_all** over the expert mesh axis.  Tokens are laid
out as ``(world * n_expert * capacity, H)`` with per-slot validity carried in
the dispatch mask (see ops/moe.topk_gating) instead of ragged counts.  These
functions must run inside shard_map over the expert axis; for the
annotation-based path (GSPMD inserts the exchange automatically) use
``paddle_tpu.ops.moe.moe_ffn``.
"""

from __future__ import annotations

import numpy as np
from jax import lax

__all__ = ["global_scatter", "global_gather"]


def _resolve_axis(group):
    if group is None:
        return "data"
    return getattr(group, "axis_name", group)


def global_scatter(x, local_count=None, global_count=None, group=None,
                   use_calc_stream=True):
    """Send each rank's per-destination token blocks to their experts.

    ``x``: local ``(world * n_expert * capacity, H)`` — row block ``w`` holds
    the tokens this rank routes to rank ``w``'s experts (capacity-padded).
    Returns ``(world * n_expert * capacity, H)``: the tokens this rank's
    experts received from every rank.  ``local_count``/``global_count`` are
    accepted for API parity; when given as concrete values they must be
    uniform (the static-shape exchange always moves full capacity blocks) —
    ragged counts raise.  Traced counts cannot be checked and are ignored.
    """
    axis = _resolve_axis(group)
    world = lax.psum(1, axis)
    rows, H = x.shape
    for name, counts in (("local_count", local_count),
                         ("global_count", global_count)):
        if counts is None:
            continue
        try:
            cvals = np.unique(np.asarray(counts))
        except Exception:  # traced inside jit — cannot validate
            continue
        if cvals.size > 1:
            raise ValueError(
                f"TPU global_scatter moves uniform capacity blocks; ragged "
                f"{name}={cvals.tolist()} is not supported — pad each "
                f"expert's tokens to a fixed capacity (see ops/moe.py)")
    if rows % world != 0:
        raise ValueError(f"global_scatter rows ({rows}) must be a multiple of "
                         f"the '{axis}' axis size ({world})")
    return lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)


def global_gather(x, local_count=None, global_count=None, group=None,
                  use_calc_stream=True):
    """Inverse of :func:`global_scatter` — return expert outputs to the ranks
    that sent the tokens."""
    return global_scatter(x, local_count, global_count, group, use_calc_stream)
