"""Expert-parallel exchange utilities.

Reference: python/paddle/distributed/utils.py:57 ``global_scatter`` / :179
``global_gather`` — ragged token exchange driven by per-expert counts
(grouped ncclSend/Recv loops, operators/collective/global_scatter_op.cu.cc).

TPU-native design, two tiers:

1. **No counts** (the annotation-friendly path): a uniform-capacity
   ``all_to_all`` moving fixed ``(world * n_expert * capacity, H)`` blocks,
   with validity carried in the dispatch mask (ops/moe.topk_gating).

2. **Counts given** (reference-faithful ragged semantics): XLA collectives
   are static-shape, so the ragged exchange is expressed as *pad → all_to_all
   → sort-compact*: rows are gathered into per-destination-rank blocks of a
   static worst-case size, exchanged with one ``all_to_all``, then an
   ``argsort`` on (expert, source-rank, index) keys compacts the valid rows
   to the front in exactly the reference's expert-major receive order.  All
   index math is traced (counts may be jit-time values); only the block size
   (≤ the static local token count) is static.  Overall cost is one
   all_to_all plus O(T log T) device-side sorting — no host sync, no ragged
   sends.

Both tiers must run inside shard_map over the expert axis; for the
annotation-based path (GSPMD inserts the exchange automatically) use
``paddle_tpu.ops.moe.moe_ffn``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["global_scatter", "global_gather",
           "ragged_global_scatter", "ragged_global_gather"]


def _resolve_axis(group):
    if group is None:
        return "data"
    return getattr(group, "axis_name", group)


# --------------------------------------------------------------------------
# tier 1: uniform capacity blocks
# --------------------------------------------------------------------------

def _uniform_exchange(x, axis):
    world = lax.psum(1, axis)
    rows, H = x.shape
    if rows % world != 0:
        raise ValueError(f"global_scatter rows ({rows}) must be a multiple of "
                         f"the '{axis}' axis size ({world})")
    return lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)


# --------------------------------------------------------------------------
# tier 2: ragged counts (reference global_scatter semantics)
# --------------------------------------------------------------------------

def _rank_blocks_from_ragged(x, rank_count, rank_offset, W, B):
    """(T, H) ragged-grouped rows → (W, B, H) per-destination blocks."""
    T, H = x.shape
    j = jnp.arange(B)[None, :]                       # (1, B)
    src = rank_offset[:, None] + j                   # (W, B)
    valid = j < rank_count[:, None]
    src = jnp.clip(src, 0, T - 1)
    blocks = x[src.reshape(-1)].reshape(W, B, H)
    return jnp.where(valid[:, :, None], blocks, 0), valid


def _ragged_from_rank_blocks(blocks, rank_count, rank_offset, T):
    """(W, B, H) blocks → (T, H) ragged-grouped rows (inverse of above)."""
    W, B, H = blocks.shape
    r = jnp.arange(T)                                # (T,)
    cum_incl = jnp.cumsum(rank_count)                # (W,)
    w = jnp.sum(r[:, None] >= cum_incl[None, :], axis=1)      # (T,)
    j = r - rank_offset[w]
    flat = blocks.reshape(W * B, H)
    idx = jnp.clip(w * B + j, 0, W * B - 1)
    return flat[idx]


def ragged_global_scatter(x, local_count, group=None, block: Optional[int] = None
                          ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Reference-semantics ragged scatter under static shapes.

    ``x``: (T, H) local tokens grouped by destination expert — rows
    [offsets[d], offsets[d] + local_count[d]) go to global expert ``d``
    (destination rank ``d // El``, its local expert ``d % El``), where
    offsets = exclusive-cumsum(local_count) and El = n_experts per rank.

    Returns ``(out, recv_counts, perm)``:
    - ``out`` (W*B, H): received tokens compacted to the front in the
      reference's receive order — grouped by (local expert, source rank),
      expert-major; rows past ``recv_counts.sum()`` are zero padding.
    - ``recv_counts`` (W, El): tokens received from each source rank for
      each local expert (the reference's ``global_count``).
    - ``perm``: opaque permutation to pass to :func:`ragged_global_gather`.
    """
    axis = _resolve_axis(group)
    W = lax.psum(1, axis)
    T, H = x.shape
    El = jnp.shape(local_count)[0] // W
    if block is not None and block < T:
        # a too-small block silently drops tokens in the masked gather; only
        # concrete counts can prove safety, so traced counts require the
        # always-safe default (block = T, the worst case: all rows to one rank)
        try:
            rank_max = int(np.max(np.asarray(local_count)
                                  .reshape(W, El).sum(axis=1)))
        except Exception:
            raise ValueError(
                f"block={block} < local rows ({T}) cannot be verified against "
                f"traced counts; omit block (worst-case T is always safe)")
        if rank_max > block:
            raise ValueError(
                f"block={block} smaller than the largest per-rank send "
                f"({rank_max}) — tokens would be dropped")
    local_count = jnp.asarray(local_count, jnp.int32)
    B = T if block is None else block

    lc = local_count.reshape(W, El)
    rank_count = jnp.sum(lc, axis=1)                          # (W,)
    rank_offset = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                   jnp.cumsum(rank_count)[:-1]])
    send, _ = _rank_blocks_from_ragged(x, rank_count, rank_offset, W, B)

    # counts exchange: recv_counts[w, el] = tokens source rank w sent for my
    # local expert el
    recv_counts = lax.all_to_all(lc, axis, split_axis=0, concat_axis=0,
                                 tiled=True).reshape(W, El)
    recv = lax.all_to_all(send, axis, split_axis=0, concat_axis=0)
    recv = recv.reshape(W, B, H)

    # compact: row j of source-rank block w belongs to local expert
    # el = #(inclusive-cumsum entries <= j); order key (el, w, i_within)
    cum_incl = jnp.cumsum(recv_counts, axis=1)                # (W, El)
    cum_excl = cum_incl - recv_counts
    j = jnp.arange(B)[None, :]
    el = jnp.sum(j[:, :, None] >= cum_incl[:, None, :], axis=2)  # (W, B)
    el = jnp.minimum(el, El - 1)
    i_within = j - jnp.take_along_axis(cum_excl, el, axis=1)
    valid = j < jnp.sum(recv_counts, axis=1)[:, None]
    WB = W * B
    big = jnp.asarray(WB * (El + 1), jnp.int32)
    key = jnp.where(
        valid,
        el * WB + jnp.arange(W)[:, None] * B + i_within,
        big + jnp.arange(B)[None, :] + jnp.arange(W)[:, None] * B)
    perm = jnp.argsort(key.reshape(-1))
    out = recv.reshape(WB, H)[perm]
    return out, recv_counts, perm


def ragged_global_gather(y, local_count, perm, rows: int, group=None):
    """Inverse of :func:`ragged_global_scatter`: route expert outputs back to
    the ranks/rows that sent the tokens.

    ``y`` (W*B, H) must be in the compacted receive order produced by the
    matching scatter; ``rows`` is the scatter input's static row count
    (``x.shape[0]``).  Returns (rows, H) in the original ragged layout.
    """
    axis = _resolve_axis(group)
    W = lax.psum(1, axis)
    local_count = jnp.asarray(local_count, jnp.int32)
    El = local_count.shape[0] // W
    WB, H = y.shape
    B = WB // W

    inv_perm = jnp.argsort(perm)
    blocks = y[inv_perm].reshape(W, B, H)
    back = lax.all_to_all(blocks, axis, split_axis=0, concat_axis=0)
    back = back.reshape(W, B, H)

    lc = local_count.reshape(W, El)
    rank_count = jnp.sum(lc, axis=1)
    rank_offset = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                   jnp.cumsum(rank_count)[:-1]])
    return _ragged_from_rank_blocks(back, rank_count, rank_offset, int(rows))


# --------------------------------------------------------------------------
# public API (reference signatures)
# --------------------------------------------------------------------------

def _counts_uniform_or_none(counts):
    """True if counts are absent or provably uniform; None if traced (cannot
    tell)."""
    if counts is None:
        return True
    try:
        cvals = np.unique(np.asarray(counts))
    except Exception:  # traced inside jit — cannot validate
        return None
    return cvals.size <= 1


def global_scatter(x, local_count=None, global_count=None, group=None,
                   use_calc_stream=True):
    """Send each rank's token blocks to their experts.

    Back-compat contract (unchanged from round 1): no counts, or provably
    *uniform* counts, run the tier-1 capacity-block all_to_all on ``x``'s
    layout as-is.  *Ragged* counts raise with a pointer to the
    :func:`ragged_global_scatter`/:func:`ragged_global_gather` pair — the
    ragged exchange returns extra metadata (receive counts + permutation)
    that this reference-shaped signature cannot carry, and silently
    reordering the output here would corrupt callers written against the
    block layout.
    """
    axis = _resolve_axis(group)
    for name, counts in (("local_count", local_count),
                         ("global_count", global_count)):
        if _counts_uniform_or_none(counts) is False:
            raise ValueError(
                f"ragged {name} passed to global_scatter/global_gather; use "
                f"the ragged_global_scatter/ragged_global_gather pair, which "
                f"returns the receive counts and permutation the gather-back "
                f"needs")
    return _uniform_exchange(x, axis)


def global_gather(x, local_count=None, global_count=None, group=None,
                  use_calc_stream=True):
    """Inverse of :func:`global_scatter` (uniform tier); for the ragged tier
    use :func:`ragged_global_gather` with the saved counts + permutation."""
    return global_scatter(x, local_count, global_count, group, use_calc_stream)
