"""Hybrid-parallel topology.

Reference: python/paddle/distributed/fleet/base/topology.py —
CommunicateTopology:36 (N-D rank coordinate math) + HybridCommunicateGroup:117
(comm groups per axis, fixed nesting order dp→pp→sharding→mp).

TPU-native: the topology IS a ``jax.sharding.Mesh``.  Instead of building an
NCCL ring per axis, we build ONE device mesh whose named axes are the
parallelism dimensions; every "communication group" of the reference maps to
a mesh axis name that XLA collectives reference.  The nesting order is kept
(outermost varies slowest) so rank→coordinate math matches the reference's
checkpoint layouts.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

# canonical axis order, outer → inner (reference topology.py:361
# ["data", "pipe", "sharding", "sep", "model"])
HYBRID_AXES = ("data", "pipe", "sharding", "sep", "model")


class CommunicateTopology:
    """Pure coordinate math over an N-D rank grid (no devices needed)."""

    def __init__(self, hybrid_group_names: Sequence[str] = ("data", "pipe",
                                                            "sharding", "model"),
                 dims: Sequence[int] = (1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(int(d) for d in dims)
        self.coordinate = list(itertools.product(*(range(d) for d in self._dims)))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank: int):
        return self.coordinate[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return sorted(r for r, c in enumerate(self.coordinate) if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """All groups along ``axis_name`` (ranks varying only that coord)."""
        axis = self._parallel_names.index(axis_name)
        other = [self._parallel_names[i] for i in range(len(self._parallel_names))
                 if i != axis]
        groups = []
        for combo in itertools.product(*(range(self._dims[i])
                                         for i in range(len(self._dims)) if i != axis)):
            fixed = dict(zip(other, combo))
            group = []
            for v in range(self._dims[axis]):
                fixed[axis_name] = v
                group.append(self.get_rank(**fixed))
            groups.append(group)
        return groups

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = list(self.get_coord(global_rank))
        for name, v in kwargs.items():
            coord[self._parallel_names.index(name)] = v
        return self._coord2rank[tuple(coord)]


class HybridCommunicateGroup:
    """Reference HybridCommunicateGroup, re-expressed over a jax Mesh.

    Group handles become (mesh, axis_name) pairs; `get_*_parallel_group()`
    returns a lightweight Group object whose `.name` is the mesh axis —
    usable directly in shard_map / psum.
    """

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 dp_degree: int = 1, mp_degree: int = 1, pp_degree: int = 1,
                 sharding_degree: int = 1, sep_degree: int = 1,
                 devices: Optional[Sequence] = None, order: Sequence[str] = None,
                 virtual_pp_degree: int = 1):
        if topology is not None:
            self._topo = topology
            dims = {n: topology.get_dim(n) for n in topology.get_hybrid_group_names()}
            dp_degree = dims.get("data", 1)
            pp_degree = dims.get("pipe", 1)
            sharding_degree = dims.get("sharding", 1)
            sep_degree = dims.get("sep", 1)
            mp_degree = dims.get("model", 1)
        self._dp_degree = dp_degree
        self._mp_degree = mp_degree
        self._pp_degree = pp_degree
        self._sharding_degree = sharding_degree
        self._sep_degree = sep_degree
        # interleaved-schedule chunk count per pipe device (not a mesh axis:
        # chunks live on the stacked-layer dim, ≙ reference
        # num_virtual_pipeline_stages on PipelineLayer)
        self._virtual_pp_degree = max(int(virtual_pp_degree), 1)
        names = list(order) if order else list(HYBRID_AXES)
        degrees = {"data": dp_degree, "pipe": pp_degree, "sharding": sharding_degree,
                   "sep": sep_degree, "model": mp_degree}
        self._axis_names = [n for n in names if degrees.get(n, 1) >= 1]
        self._dims = [degrees.get(n, 1) for n in self._axis_names]
        if topology is None:
            self._topo = CommunicateTopology(self._axis_names, self._dims)
        self.nranks = int(np.prod(self._dims))
        self._devices = list(devices) if devices is not None else None
        self._mesh: Optional[Mesh] = None
        from . import env
        self.global_rank = env.get_rank() if self.nranks > 1 else 0

    # ----------------------------------------------------------- mesh build
    def build_mesh(self, devices: Optional[Sequence] = None) -> Mesh:
        """Construct the jax Mesh (≙ _init_hybrid_parallel_env building all
        NCCL rings at once).  Mesh axes in nesting order; pod-slice-aware
        device ordering can be injected via ``devices``."""
        if self._mesh is not None and devices is None:
            return self._mesh
        from ..core.device import local_devices
        devs = list(devices if devices is not None
                    else (self._devices or local_devices()))
        if len(devs) < self.nranks:
            raise ValueError(f"need {self.nranks} devices, have {len(devs)}")
        arr = np.array(devs[: self.nranks]).reshape(self._dims)
        self._mesh = Mesh(arr, tuple(self._axis_names))
        return self._mesh

    @property
    def mesh(self) -> Mesh:
        return self.build_mesh()

    def axis_name(self, logical: str) -> str:
        return {"dp": "data", "pp": "pipe", "sharding": "sharding",
                "sep": "sep", "mp": "model"}.get(logical, logical)

    # ------------------------------------------------- reference API surface
    def get_hybrid_group_names(self):
        return self._axis_names

    def get_global_rank(self) -> int:
        return self.global_rank

    def _axis_rank(self, name: str) -> int:
        if name not in self._axis_names or self.nranks == 1:
            return 0
        coord = self._topo.get_coord(self.global_rank)
        return coord[self._axis_names.index(name)]

    def get_data_parallel_rank(self) -> int:
        return self._axis_rank("data")

    def get_model_parallel_rank(self) -> int:
        return self._axis_rank("model")

    def get_stage_id(self) -> int:
        return self._axis_rank("pipe")

    def get_sharding_parallel_rank(self) -> int:
        return self._axis_rank("sharding")

    def get_sep_parallel_rank(self) -> int:
        return self._axis_rank("sep")

    def get_data_parallel_world_size(self) -> int:
        return self._dp_degree

    def get_model_parallel_world_size(self) -> int:
        return self._mp_degree

    def get_pipe_parallel_world_size(self) -> int:
        return self._pp_degree

    def get_virtual_pipeline_degree(self) -> int:
        return self._virtual_pp_degree

    def get_sharding_parallel_world_size(self) -> int:
        return self._sharding_degree

    def get_sep_parallel_world_size(self) -> int:
        return self._sep_degree

    def _group(self, name: str):
        from .collective import Group
        ranks = self._topo.get_axis_list(name, 0) if name in self._axis_names else [0]
        return Group(ranks=list(range(self._topo.get_dim(name)
                                      if name in self._axis_names else 1)),
                     axis_name=name, hcg=self)

    def get_data_parallel_group(self):
        return self._group("data")

    def get_model_parallel_group(self):
        return self._group("model")

    def get_pipe_parallel_group(self):
        return self._group("pipe")

    def get_sharding_parallel_group(self):
        return self._group("sharding")

    def get_sep_parallel_group(self):
        return self._group("sep")

    def get_check_parallel_group(self, *a, **k):
        return self._group("model")

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    def get_p2p_groups(self):
        return None

    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and \
                self._sharding_degree == 1 and self._dp_degree > 1:
            return "DataParallel"
        if self._sharding_degree > 1 and self._mp_degree == 1 and self._pp_degree == 1:
            return "ShardingParallel"
        if self._pp_degree > 1:
            return "PipelineParallel"
        if self._mp_degree > 1:
            return "TensorParallel"
        return "Serial"


_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg
