"""Rendezvous / membership stores.

Reference: fleet/elastic/manager.py:130 (etcd client: host registration,
heartbeat leases, watches) and the raw-TCP NCCL-id bootstrap
(gen_comm_id_helper.cc).  Two backends behind one interface:

- ``FileStore`` — a directory on a local disk or an NFSv4 mount; the
  original single-host/shared-fs path.  ``add`` needs working advisory
  locks, which object-store mounts (gcsfuse) don't provide — multi-host
  jobs should use the TCP store.
- ``TCPStore`` — client for the native store server (csrc/kv_store.cpp), a
  single C++ poll-loop the launcher's rank-0 hosts in-process.  This is the
  multi-host path: workers dial ``tcp://master:port`` — no etcd, no shared
  filesystem needed.

``make_store("tcp://host:port" | "/some/dir")`` picks the backend; the
elastic manager and launcher accept either form.
"""

from __future__ import annotations

import ctypes
import logging
import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_OPS = {"SET": 0, "GET": 1, "ADD": 2, "WAIT": 3, "DEL": 4, "LIST": 5}


class StoreServer:
    """In-process native TCP store server (rank-0 side).  port=0 auto-picks;
    read the bound port from ``.port``."""

    def __init__(self, port: int = 0):
        from ..csrc import load_library
        self._lib = load_library("kv_store")
        self._lib.kv_server_start.restype = ctypes.c_void_p
        self._lib.kv_server_start.argtypes = [ctypes.c_int]
        self._lib.kv_server_port.restype = ctypes.c_int
        self._lib.kv_server_port.argtypes = [ctypes.c_void_p]
        self._lib.kv_server_stop.argtypes = [ctypes.c_void_p]
        self._handle = self._lib.kv_server_start(port)
        if not self._handle:
            raise OSError(f"kv_store server failed to bind port {port}")
        self.port = self._lib.kv_server_port(self._handle)

    def stop(self):
        if self._handle:
            self._lib.kv_server_stop(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC ordering
        try:
            self.stop()
        except (OSError, AttributeError) as e:
            # half-constructed instance (AttributeError) or the native lib
            # failing mid-teardown; a dead server at GC is worth one debug
            # line, not a raised-in-__del__ warning
            logger.debug("StoreServer.__del__: stop failed: %s", e)


_UNSET = object()  # wait(timeout=None) must mean "block forever"


class TCPStore:
    """Client for the native store.  Thread-safe (one lock per connection);
    WAIT blocks server-side, so no polling traffic.  A request that dies
    mid-flight (timeout / connection error) poisons the framing of the
    persistent connection, so the socket is dropped and redialed on the next
    request — the server unparks any WAIT this fd held when it sees the
    close."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.addr = (host, int(port))
        self.timeout = timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        with self._lock:
            self._dial(timeout)

    def _dial(self, timeout: float):
        deadline = time.time() + timeout
        last_err: Optional[Exception] = None
        while True:  # the server may still be coming up on rank 0
            try:
                self._sock = socket.create_connection(self.addr, timeout=5.0)
                break
            except OSError as e:
                last_err = e
                if time.time() >= deadline:
                    raise TimeoutError(
                        f"store at {self.addr[0]}:{self.addr[1]} "
                        f"unreachable: {last_err}")
                time.sleep(0.2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    # ------------------------------------------------------------- wire I/O
    def _recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("store connection closed")
            out += chunk
        return out

    def _request(self, op: str, key: bytes, val: bytes = b"",
                 timeout=_UNSET) -> Tuple[int, bytes]:
        with self._lock:
            if self._sock is None:
                self._dial(self.timeout)
            try:
                self._sock.settimeout(
                    self.timeout if timeout is _UNSET else timeout)
                self._sock.sendall(
                    struct.pack("<BII", _OPS[op], len(key), len(val))
                    + key + val)
                status = self._recv_exact(1)[0]
                (vlen,) = struct.unpack("<I", self._recv_exact(4))
                return status, self._recv_exact(vlen)
            except (OSError, ConnectionError):
                # mid-request failure ⇒ unknown framing state: drop the conn
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                raise

    # ------------------------------------------------------------ store API
    def set(self, key: str, value: bytes):
        self._request("SET", key.encode(), value)

    def get(self, key: str) -> Optional[bytes]:
        status, val = self._request("GET", key.encode())
        return None if status else val

    def add(self, key: str, delta: int = 1) -> int:
        _, val = self._request("ADD", key.encode(), struct.pack("<q", delta))
        return struct.unpack("<q", val)[0]

    def wait(self, key: str, timeout: Optional[float] = None) -> bytes:
        _, val = self._request("WAIT", key.encode(), timeout=timeout)
        return val

    def delete(self, key: str):
        self._request("DEL", key.encode())

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        _, buf = self._request("LIST", prefix.encode())
        out, off = {}, 0
        while off < len(buf):
            (klen,) = struct.unpack_from("<I", buf, off)
            key = buf[off + 4:off + 4 + klen].decode()
            off += 4 + klen
            (vlen,) = struct.unpack_from("<I", buf, off)
            out[key] = buf[off + 4:off + 4 + vlen]
            off += 4 + vlen
        return out

    def close(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class FileStore:
    """Directory-backed store with the same API (single host / shared mount)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _p(self, key: str) -> str:
        return os.path.join(self.path, key)

    def set(self, key: str, value: bytes):
        tmp = self._p(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, self._p(key))

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._p(key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def add(self, key: str, delta: int = 1) -> int:
        # flock-locked read-modify-write on a persistent per-key lock file.
        # The kernel drops the lock when the holder dies (SIGKILL included —
        # the exact fault elastic exists for), so there is no stale-lock
        # heuristic and no steal race: the previous O_EXCL+mtime scheme could
        # unlink a *fresh* holder's lock between the staleness check and the
        # unlink, admitting two writers and losing an increment.
        #
        # Deployment contract: advisory locking must actually reach the other
        # writers — true on a local filesystem (one host, the common case)
        # and on NFSv4 mounts (server-side lockd).  Object-store mounts like
        # gcsfuse implement NO file locking (each host would lock privately);
        # for those, counters must go through the TCP store
        # (``tcp://host:port``), which is the designed multi-host path.
        import fcntl
        lock = self._p(key) + ".lock"
        deadline = time.time() + 10.0
        fd = os.open(lock, os.O_CREAT | os.O_WRONLY)
        try:
            while True:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    break
                except BlockingIOError:
                    if time.time() > deadline:
                        raise TimeoutError(f"store lock stuck: {lock}")
                    time.sleep(0.01)
            cur = self.get(key)
            if cur and len(cur) != 8:
                # same contract as the TCP backend: ADD on a key holding a
                # non-counter value is a protocol error (OSError), never a
                # silent clobber
                raise OSError(f"add({key!r}): existing value is not a counter")
            new = (struct.unpack("<q", cur)[0] if cur else 0) + delta
            self.set(key, struct.pack("<q", new))
            return new
        finally:
            os.close(fd)  # closing the fd releases the flock

    def wait(self, key: str, timeout: Optional[float] = None) -> bytes:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            val = self.get(key)
            if val is not None:
                return val
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(f"wait({key!r}) timed out")
            time.sleep(0.05)

    def delete(self, key: str):
        try:
            os.unlink(self._p(key))
        except OSError:
            pass

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        out = {}
        try:
            names = os.listdir(self.path)
        except OSError:
            return out
        for fn in names:
            if fn.startswith(prefix) and not fn.endswith((".tmp", ".lock")):
                val = self.get(fn)
                if val is not None:
                    out[fn] = val
        return out

    def close(self):
        pass


def make_store(target: str, timeout: float = 60.0):
    """``tcp://host:port`` → TCPStore; anything else → FileStore(dir)."""
    if target.startswith("tcp://"):
        hostport = target[len("tcp://"):]
        host, _, port = hostport.rpartition(":")
        return TCPStore(host or "127.0.0.1", int(port), timeout=timeout)
    return FileStore(target)
