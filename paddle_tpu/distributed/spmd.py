"""SPMD hybrid-parallel engine.

This module is the TPU-native replacement for the reference's entire
program-rewriting distributed stack:

- meta-optimizers inserting c_allreduce/c_broadcast (sharding_optimizer.py,
  raw_program_optimizer.py, tensor_parallel_optimizer.py) → sharding
  annotations + GSPMD;
- NCCL ring bootstrap (gen_comm_id_helper.cc, collective_helper.h) → a
  ``jax.sharding.Mesh``;
- the 1F1B SectionWorker / PipelineParallel runtime (section_worker.cc,
  pipeline_parallel.py) → a shard_map micro-batch pipeline over the "pipe"
  mesh axis with ``ppermute`` hops (explicit only on that axis; all other
  axes stay under GSPMD via partial-auto shard_map).

Sharding rules (build_param_specs):
- TP:   params carry ``_dims_mapping = {dim: axis}`` (set by mp_layers) →
        PartitionSpec entries on "model".
- PP:   params carry ``_pp_stage`` or are stage-stacked on dim 0 ("pipe").
- ZeRO: optimizer slots (+ params at stage 3) additionally sharded over
        "sharding" on the largest divisible free dim.
- DP:   batch dim of inputs on "data"; params replicated over "data".
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..core import rng
from ..core.tensor import Tensor

# Sharding-spec inference lives in sharding_rules.py (THE array-layout
# module) since PR 16; re-exported here because every trainer and half the
# test suite historically imported it from spmd.
from .sharding_rules import (_slot_spec, _spec_for_param, batch_spec,
                             build_param_specs, build_state_shardings,
                             replicated_spec)


# --------------------------------------------------------------------------
# shard_map micro-batch pipeline as a lax.scan over ticks.
#
# Schedule: M+S-1 ticks, each tick runs one stage body per device and one
# ppermute hop — the same tick count (and thus the same bubble fraction
# (S-1)/(M+S-1)) as the reference's 1F1B (section_worker.cc:62-137).  The
# scan body is constant-size, so the jaxpr does NOT grow with M (the round-1
# unrolled reduce blew up compile time past M≈32).  1F1B's remaining benefit
# over GPipe is activation scheduling; here per-tick jax.checkpoint bounds
# stored residuals to the tick boundaries (one micro-batch activation per
# tick) and interiors are recomputed in the backward scan — the TPU analog
# of 1F1B's bounded in-flight window.
# --------------------------------------------------------------------------

# The VMA seam, resolved ONCE at import and pinned by
# tests/test_spmd_vma_seam.py: shard_map's varying-manual-axes checker and
# its cast primitive have moved across JAX releases (jax.core.get_aval ->
# jax._src.core, pvary -> pcast).  An incompatible future JAX must fail HERE,
# loudly, not turn the pipeline's varying-cast into a silent no-op
# (VERDICT r3 weak #4).
try:  # jax.core.get_aval warns/moves across versions; prefer the _src home
    from jax._src.core import get_aval as _get_aval
except ImportError:  # pragma: no cover - older/newer layout
    _get_aval = jax.core.get_aval

# shard_map itself has moved too: jax.experimental.shard_map -> top-level
# jax.shard_map, and its kwargs renamed with it (check_rep -> check_vma,
# auto -> axis_names).  Resolve ONCE here and translate the modern spelling
# to whatever this JAX accepts — every call site in the framework routes
# through this adapter, never the bare jax attribute (which raises on
# pre-promotion releases).
try:
    from jax import shard_map as _shard_map_impl  # jax >= 0.6 export
except ImportError:  # pragma: no cover - experimental home on older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl

import inspect as _inspect

_SHARD_MAP_KW = frozenset(
    _inspect.signature(_shard_map_impl).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None):
    """``jax.shard_map`` in its MODERN spelling on any supported JAX:
    ``check_vma`` maps to ``check_rep`` and ``axis_names`` (the manual
    axes) to ``auto`` (its complement over the mesh) on releases that
    predate the renames."""
    kw = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if check_vma is not None:
        kw["check_vma" if "check_vma" in _SHARD_MAP_KW
           else "check_rep"] = check_vma
    if axis_names is not None:
        if "axis_names" in _SHARD_MAP_KW:
            kw["axis_names"] = axis_names
        else:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kw["auto"] = auto
    return _shard_map_impl(f, **kw)

#: whether this JAX tracks varying-manual-axes on avals at all (older
#: releases: no VMA checking, casting is correctly a no-op)
VMA_AVALS = hasattr(jax.core.ShapedArray((), np.dtype(np.float32)), "vma")

if hasattr(jax.lax, "pcast"):
    def _cast_varying(x, axis):
        return jax.lax.pcast(x, (axis,), to="varying")
elif hasattr(jax.lax, "pvary"):  # pragma: no cover - pre-pcast JAX
    def _cast_varying(x, axis):
        return jax.lax.pvary(x, (axis,))
elif VMA_AVALS:  # pragma: no cover - VMA checking with no cast primitive
    raise ImportError(
        "this JAX tracks varying-manual-axes but exposes neither lax.pcast "
        "nor lax.pvary; the spmd pipeline cannot mark carries varying — "
        "update ensure_varying for this JAX version")
else:  # pragma: no cover - pre-VMA JAX: nothing to mark
    _cast_varying = None


def ensure_varying(x, axis):
    """Mark ``x`` device-varying over ``axis`` for shard_map's VMA checker,
    as a no-op when it already is (pcast rejects varying→varying)."""
    if not VMA_AVALS:
        return x
    # no blanket except here: if get_aval or .vma fails on a valid pipeline
    # carry, that is an incompatibility to surface, not to swallow
    if axis in _get_aval(x).vma:
        return x
    return _cast_varying(x, axis)


def spmd_pipeline(stage_fn: Callable, stage_params, microbatches, n_stages: int,
                  axis: str = "pipe", remat_ticks: bool = True):
    """Run inside shard_map over ``axis``.

    stage_fn(stage_params, x, microbatch_index) -> y ; stage_params is the
    LOCAL stage's parameter shard (leading stage dim already split away).
    ``microbatches``: (M, mb, ...) — meaningful on stage 0, replicated
    elsewhere.  Returns (M, mb, ...) outputs meaningful on the LAST stage
    (broadcast back to all stages).
    """
    M = microbatches.shape[0]
    S = n_stages
    stage = jax.lax.axis_index(axis)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(state, t):
        mb_idx = jnp.minimum(t, M - 1)
        inp = jnp.where(stage == 0, microbatches[mb_idx], state)
        y = stage_fn(stage_params, inp, mb_idx)
        return jax.lax.ppermute(y, axis, fwd_perm), y

    if remat_ticks:
        tick = jax.checkpoint(tick)
    # shard_map varying-manual-axes check (jax>=0.7): the carry becomes
    # device-varying after the first ppermute, so the init must be too
    carry0 = ensure_varying(jnp.zeros_like(microbatches[0]), axis)
    _, ys = jax.lax.scan(tick, carry0, jnp.arange(M + S - 1))
    # ticks S-1 .. M+S-2 are the last stage's M finished micro-batches
    outputs = ys[S - 1:]
    # broadcast final outputs from the last stage to every stage
    # (masked psum — ppermute can't scatter one source to many)
    outputs = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs))
    outputs = jax.lax.psum(outputs, axis)
    return outputs


def spmd_pipeline_interleaved(stage_fn: Callable, chunk_params, microbatches,
                              n_stages: int, n_chunks: int, axis: str = "pipe",
                              remat_ticks: bool = True):
    """Megatron-style interleaved (virtual-pipeline) schedule as a lax.scan.

    ≙ the reference's virtual_pipeline_degree path (pipeline_parallel.py
    _forward_backward_pipeline interleaved branch; pp_layers.py
    get_stage_from_index maps layer→(stage, chunk)).  Device ``d`` holds
    ``V = n_chunks`` model chunks; chunk ``v`` on device ``d`` is global
    stage ``g = v*S + d``.  Each scan tick executes ONE chunk (cost ≈ 1/V of
    a non-interleaved stage) and one ring ``ppermute`` hop:

    - slot count is ``M*V + S - 1`` chunk-slots, so fill+drain cost is
      ``(S-1)/V`` stage-times instead of ``S-1`` — the bubble shrinks by the
      virtual degree, same as the reference's interleaved 1F1B;
    - the schedule is conflict-free: device-local clock ``w = u - d`` decodes
      uniquely to ``(microbatch, chunk) = (q//V*S + w%S, q%V)``, ``q = w//S``
      (requires ``M % S == 0``, the same constraint Megatron imposes);
    - AD reverses the scan, so the backward sweep gets the same reduced
      bubble; ``jax.checkpoint`` on the tick bounds live activations to one
      micro-batch per slot.

    ``stage_fn(chunk_local_params, x, mb_index, chunk_index) -> y``;
    ``chunk_params``: device-local pytree with leading dim ``V``;
    ``microbatches``: (M, mb, ...) meaningful on stage 0.  Returns
    (M, mb, ...) finished outputs broadcast from the last stage.
    """
    M = microbatches.shape[0]
    S, V = n_stages, n_chunks
    if M % S:
        raise ValueError(
            f"n_microbatches ({M}) must be a multiple of the pipeline "
            f"degree ({S}) for the interleaved schedule")
    stage = jax.lax.axis_index(axis)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, u):
        # device-local chunk clock; clipped decode is safe because inactive
        # slots' outputs are never selected by an active receiver
        w = jnp.clip(u - stage, 0, M * V - 1)
        j = w % S
        q = w // S
        v = q % V
        m = (q // V) * S + j
        chp = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, v, 0, keepdims=False),
            chunk_params)
        inp = jnp.where((stage == 0) & (v == 0), microbatches[m], carry)
        y = stage_fn(chp, inp, m, v)
        return jax.lax.ppermute(y, axis, fwd_perm), y

    if remat_ticks:
        tick = jax.checkpoint(tick)
    carry0 = ensure_varying(jnp.zeros_like(microbatches[0]), axis)
    _, ys = jax.lax.scan(tick, carry0, jnp.arange(M * V + S - 1))
    # micro-batch m = r*S + j leaves chunk V-1 on the last stage at slot
    # u = S*V*(r+1) + j - 1  (w_out = j + S*(V-1) + S*V*r, u = w_out + S-1)
    m_idx = jnp.arange(M)
    out_slots = S * V * (m_idx // S + 1) + (m_idx % S) - 1
    outputs = ys[out_slots]
    outputs = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, axis)


# --------------------------------------------------------------------------
# distributed train step builder
# --------------------------------------------------------------------------

def make_spmd_train_step(layer, loss_fn, optimizer, hcg, zero_stage: int = 0,
                         accumulate_steps: int = 1, donate: bool = True,
                         monitor=None, grad_comm=None):
    """GSPMD train step over the hybrid mesh (dp × sharding × model [+ sep]).

    ≙ §3.3 of the survey: what the reference achieves by rewriting the
    program with c_ops, we achieve by jitting the SAME step function with
    NamedSharding on params/optimizer-state/batch.  XLA inserts: dp grad
    allreduce (Reducer), mp activation allreduces (TP), ZeRO
    reduce-scatter/all-gathers — scheduled on ICI.
    """
    from ..jit.functional import functionalize, _wrap, _unwrap, wrap_tree
    from .grad_comm import apply_policy_local, comm_info, resolve_policy

    policy = resolve_policy(grad_comm)
    mesh = hcg.mesh
    apply_fn, params0, buffers0 = functionalize(layer)
    opt_state0 = optimizer.init_state(params0)
    state0 = {"params": params0, "opt": opt_state0, "buffers": buffers0}

    p_specs = build_param_specs(params0, mesh, layer, zero_stage)
    state_sh = build_state_shardings(state0, p_specs, mesh, zero_stage, params0)
    if policy.stateful:
        state0["comm_e"] = policy.residual_for(params0)
        state_sh["comm_e"] = NamedSharding(mesh, replicated_spec())
    batch_sh = NamedSharding(mesh, batch_spec(mesh))
    rep = NamedSharding(mesh, replicated_spec())

    def place(state):
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), state, state_sh,
            is_leaf=lambda x: hasattr(x, "shape"))

    def loss_of(p, b, key, inputs, labels):
        out, new_b = apply_fn(p, b, *inputs, rng_key=key, training=True)
        main = out[0] if isinstance(out, (list, tuple)) else out
        loss_t = loss_fn(_wrap(main), *wrap_tree(labels))
        return _unwrap(loss_t), (new_b, main)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state, key, lr, inputs, labels):
        if accumulate_steps > 1:
            def micro(idx, acc):
                g_acc, l_acc = acc
                mb = jax.tree_util.tree_map(
                    lambda x: x.reshape((accumulate_steps,
                                         x.shape[0] // accumulate_steps)
                                        + x.shape[1:])[idx], (inputs, labels))
                (l, _), g = jax.value_and_grad(loss_of, has_aux=True)(
                    state["params"], state["buffers"],
                    jax.random.fold_in(key, idx), mb[0], mb[1])
                return (jax.tree_util.tree_map(jnp.add, g_acc, g), l_acc + l)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, state["params"])
            grads, loss = jax.lax.fori_loop(
                0, accumulate_steps, micro, (zeros, jnp.zeros([], jnp.float32)))
            grads = jax.tree_util.tree_map(lambda g: g / accumulate_steps, grads)
            loss = loss / accumulate_steps
            new_b = state["buffers"]
        else:
            (loss, (new_b, _)), grads = jax.value_and_grad(loss_of, has_aux=True)(
                state["params"], state["buffers"], key, inputs, labels)
        grads, comm_state = apply_policy_local(policy, grads, state)
        new_params, new_opt = optimizer.update(grads, state["opt"], state["params"],
                                               lr=lr)
        # keep shardings stable across steps
        new_params = jax.lax.with_sharding_constraint(
            new_params, {k: NamedSharding(mesh, p_specs[k]) for k in new_params})
        return {"params": new_params, "opt": new_opt, "buffers": new_b,
                **comm_state}, loss

    from ..telemetry import instrument_train_step
    return instrument_train_step(step, monitor, "spmd",
                                 comm=comm_info(params0, policy)), \
        place(state0), state_sh


def _make_gspmd_step(loss_of, optimizer, mesh, p_specs, donate,
                     grad_comm=None):
    """The shared jitted step kernel: fwd+bwd+update with params
    re-constrained each step so shardings stay stable under donation.

    ``grad_comm``: gradient-communication policy applied in LOCAL mode at
    the post-backward seam (GSPMD owns the collective schedule here —
    the policy pins the exchanged gradient's numerics and byte
    accounting; see distributed/grad_comm.py).  Stateful policies thread
    a flat ``"comm_e"`` residual through the state."""
    from .grad_comm import apply_policy_local, resolve_policy
    policy = resolve_policy(grad_comm)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state, lr, *batch):
        loss, grads = jax.value_and_grad(loss_of)(state["params"], *batch)
        grads, comm_state = apply_policy_local(policy, grads, state)
        new_params, new_opt = optimizer.update(grads, state["opt"],
                                               state["params"], lr=lr)
        new_params = jax.lax.with_sharding_constraint(
            new_params, {k: NamedSharding(mesh, p_specs[k]) for k in new_params})
        return {"params": new_params, "opt": new_opt, "buffers": {},
                **comm_state}, loss
    return step


def make_gspmd_step_from_loss(loss_of, params0, optimizer, mesh, layer=None,
                              zero_stage: int = 0, donate: bool = True,
                              grad_comm=None):
    """Shared GSPMD train-step builder for functional models (gpt/bert/ernie).

    ``loss_of(params, *batch) -> scalar loss``.  Returns (step, state0) where
    ``step(state, lr, *batch) -> (state, loss)``; params/opt-state sharded by
    build_param_specs.  ``grad_comm`` as in ``_make_gspmd_step``.
    """
    from .grad_comm import resolve_policy
    policy = resolve_policy(grad_comm)
    p_specs = build_param_specs(params0, mesh, layer, zero_stage)
    opt_state0 = optimizer.init_state(params0)
    state0 = {"params": params0, "opt": opt_state0, "buffers": {}}
    state_sh = build_state_shardings(state0, p_specs, mesh,
                                     max(zero_stage, 1), params0)
    if policy.stateful:
        state0["comm_e"] = policy.residual_for(params0)
        state_sh["comm_e"] = NamedSharding(mesh, replicated_spec())
    step = _make_gspmd_step(loss_of, optimizer, mesh, p_specs, donate, policy)
    state0 = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), state0, state_sh,
        is_leaf=lambda x: hasattr(x, "shape"))
    return step, state0


def shard_batch(batch, hcg):
    mesh = hcg.mesh
    sh = NamedSharding(mesh, batch_spec(mesh))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(getattr(x, "_data", x), sh), batch)


def make_gspmd_sharded_init_step(loss_of, build_params, optimizer, mesh,
                                 meta_layer=None, zero_stage: int = 0,
                                 donate: bool = True, seed: int = 0,
                                 grad_comm=None):
    """Like make_gspmd_step_from_loss, but the TrainState is *initialized
    directly sharded on the mesh*: ``build_params(key)`` runs under jit with
    per-leaf out_shardings, so each device materializes only its shard and
    the host never holds a full-size copy (the 6.7B fp32 params alone are
    ~27GB host-side otherwise).  ≙ the reference's per-rank startup programs
    after sharding_optimizer pruning; the scaling-book "init on the mesh".
    """
    from .grad_comm import resolve_policy
    policy = resolve_policy(grad_comm)
    key0 = jax.random.key(seed)

    def init_state(key):
        params = build_params(key)
        state = {"params": params, "opt": optimizer.init_state(params),
                 "buffers": {}}
        if policy.stateful:
            state["comm_e"] = policy.residual_for(params)
        return state

    # one abstract trace serves both the param specs and the state layout
    state_abs = jax.eval_shape(init_state, key0)
    abs_params = state_abs["params"]
    p_specs = build_param_specs(abs_params, mesh, meta_layer, zero_stage)
    state_sh = build_state_shardings(state_abs, p_specs, mesh,
                                     max(zero_stage, 1), abs_params)
    if policy.stateful:
        state_sh["comm_e"] = NamedSharding(mesh, replicated_spec())
    # tpulint: disable=jit-in-hot-loop(one-shot sharded init at builder time, never per step)
    state0 = jax.jit(init_state, out_shardings=state_sh)(key0)
    step = _make_gspmd_step(loss_of, optimizer, mesh, p_specs, donate, policy)
    return step, state0
