"""Distributed environment (reference: python/paddle/distributed/parallel.py
init_parallel_env:71 — PADDLE_TRAINER_ID/TRAINERS_NUM env contract, mapped to
``jax.distributed`` + process metadata)."""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_parallel_env(backend: Optional[str] = None):
    """``paddle.distributed.init_parallel_env`` parity.

    Multi-host: uses jax.distributed (coordinator = PADDLE_MASTER or first
    entry of PADDLE_TRAINER_ENDPOINTS, ≙ gen_comm_id_helper.cc TCP
    rendezvous).  Single-process multi-device needs no init — XLA owns the
    devices already.
    """
    global _initialized
    if _initialized:
        return
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if world > 1:
        # do NOT probe jax.process_count() here: it would initialize the XLA
        # backend, after which jax.distributed.initialize refuses to run —
        # gate on jax's own distributed-client state instead
        from jax._src import distributed as _jdist
        if _jdist.global_state.client is None:
            coord = os.environ.get("PADDLE_MASTER")
            if coord is None:
                eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
                coord = eps.split(",")[0] if eps else None
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=world, process_id=rank)
    _initialized = True


def get_rank() -> int:
    """``paddle.distributed.get_rank`` parity."""
    env = os.environ.get("PADDLE_TRAINER_ID")
    if env is not None:
        return int(env)
    return jax.process_index()


def get_world_size() -> int:
    """``paddle.distributed.get_world_size`` parity."""
    env = os.environ.get("PADDLE_TRAINERS_NUM")
    if env is not None:
        return int(env)
    return jax.process_count()


def is_initialized() -> bool:
    return _initialized


class ParallelEnv:
    """Reference: fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", "0").split(",")[0])

    local_rank = rank
    nranks = world_size
