"""Cloud cluster helpers (reference: distributed/cloud_utils.py — derives
the trainer cluster layout from PaddleCloud env vars)."""

from __future__ import annotations

import os
from typing import List, Optional


def get_cluster_and_pod(args=None):
    """(endpoints list, my rank) from the PADDLE_* env contract."""
    eps = [e for e in os.getenv("PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]
    rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
    if not eps:
        n = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        eps = [f"127.0.0.1:{6170 + i}" for i in range(n)]
    return eps, rank


def get_cloud_cluster(args_node_ips: Optional[str] = None,
                      args_node_ip: Optional[str] = None,
                      args_port: int = 6170,
                      selected_devices: Optional[List[int]] = None):
    ips = (args_node_ips or os.getenv("PADDLE_TRAINERS", "127.0.0.1")).split(",")
    return [f"{ip}:{args_port}" for ip in ips]
