"""Collective communication API.

Reference: python/paddle/distributed/collective.py (1678 lines — Group:79,
new_group:209, all_reduce:427, all_gather:618, alltoall:1488, send:1573, …).

TPU-native semantics: a ``Group`` is a handle to a MESH AXIS, not an NCCL
ring.  Collectives have two execution modes:

- **traced** (inside ``shard_map``/``pjit`` over a Mesh): lower to
  ``jax.lax.psum/all_gather/ppermute/all_to_all`` on the group's axis name —
  XLA schedules them on ICI.  This replaces the entire c_* op family
  (operators/collective/, 12.4K LoC) + NCCLCommContext ring management +
  stream-ordering ops (c_sync_*/c_wait_*: XLA's async scheduling subsumes
  them).
- **eager** (plain Tensors, single process): world_size-1 groups are
  identity; in multi-process jax.distributed runs, eager collectives execute
  a tiny pjit over the global mesh.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from . import env

_group_counter = [0]
_groups = {}


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = a mesh axis (or an explicit rank list that we
    lay out as a 1-D mesh axis)."""

    def __init__(self, ranks: Optional[List[int]] = None, axis_name: str = "group",
                 hcg=None, gid: int = 0):
        self.ranks = list(ranks) if ranks is not None else list(
            range(env.get_world_size()))
        self.axis_name = axis_name
        self.hcg = hcg
        self.id = gid

    @property
    def nranks(self) -> int:
        return len(self.ranks)

    world_size = nranks

    @property
    def rank(self) -> int:
        r = env.get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    def is_member(self) -> bool:
        return env.get_rank() in self.ranks

    def __repr__(self):
        return f"Group(axis={self.axis_name}, ranks={self.ranks})"


_default_group: Optional[Group] = None


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        _default_group = Group(axis_name="world", gid=0)
    return _default_group


def get_group(gid: int = 0) -> Optional[Group]:
    if gid == 0:
        return _get_default_group()
    return _groups.get(gid)


def new_group(ranks: Optional[List[int]] = None, backend: Optional[str] = None,
              timeout=None) -> Group:
    """``paddle.distributed.new_group`` parity (collective.py:209) — on TPU no
    comm bootstrap happens; the group just names a (sub-)axis."""
    _group_counter[0] += 1
    gid = _group_counter[0]
    g = Group(ranks, axis_name=f"group_{gid}", gid=gid)
    _groups[gid] = g
    return g


def is_initialized() -> bool:
    return env.is_initialized() or True


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _default_group = None
        _groups.clear()


def _in_trace(x) -> bool:
    return isinstance(getattr(x, "_data", x), jax.core.Tracer)


def _axis_in_scope(axis_name) -> bool:
    try:  # proper introspection when available (jax>=0.4.31)
        from jax._src.core import get_axis_env
        return bool(get_axis_env().axis_exists(axis_name))
    except (ImportError, AttributeError):  # private API — degrade gracefully
        try:
            jax.lax.axis_index(axis_name)
            return True
        except Exception:  # unbound-name error type varies across jax versions
            return False


def _identity_if_solo(group: Group) -> bool:
    return group.nranks <= 1


def wait(tensor, group=None, use_calc_stream=True):
    """Reference collective.py:286 — stream sync; on TPU blocks on the value."""
    data = getattr(tensor, "_data", tensor)
    if not isinstance(data, jax.core.Tracer):
        jax.block_until_ready(data)


def barrier(group=None):
    """Reference collective.py:167.  Multi-host: a tiny global psum."""
    if env.get_world_size() <= 1:
        return
    x = jnp.ones([])
    jax.block_until_ready(x)


# --------------------------------------------------------------------------
# core collectives — dual mode
# --------------------------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place on eager Tensors (paddle semantics); returns the result."""
    group = group or _get_default_group()
    if _in_trace(tensor):
        fns = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
               ReduceOp.MIN: jax.lax.pmin}
        if op == ReduceOp.AVG:
            out = apply(lambda t: jax.lax.pmean(t, group.axis_name), tensor)
        elif op == ReduceOp.PROD:
            # exp(psum(log(t))) NaNs on any non-positive entry.  Correct
            # decomposition: magnitude via a log-ABS psum (zeros masked to
            # log 1), sign via a negative-count parity psum, and an any-zero
            # pmax that forces the product to exactly 0.
            def _prod(t):
                # floating inputs keep their dtype (f64 products would
                # overflow/round in a forced f32); integers go through f32
                tf = t if jnp.issubdtype(jnp.dtype(t.dtype), jnp.floating) \
                    else t.astype(jnp.float32)
                is_zero = tf == 0
                mag = jnp.exp(jax.lax.psum(
                    jnp.log(jnp.where(is_zero, 1.0, jnp.abs(tf))),
                    group.axis_name))
                neg = jax.lax.psum((tf < 0).astype(jnp.int32), group.axis_name)
                any_zero = jax.lax.pmax(is_zero.astype(jnp.int32),
                                        group.axis_name)
                signed = jnp.where(neg % 2 == 1, -mag, mag)
                out = jnp.where(any_zero > 0, 0.0, signed)
                if not jnp.issubdtype(jnp.dtype(t.dtype), jnp.floating):
                    # exp(Σlog) lands at 41.99999… for an exact 42 — round
                    # before the cast or integer products truncate off-by-one
                    out = jnp.round(out)
                return out.astype(t.dtype)
            out = apply(_prod, tensor)
        else:
            out = apply(lambda t: fns[op](t, group.axis_name), tensor)
        if isinstance(tensor, Tensor):
            tensor._adopt(out)
            return tensor
        return out
    if _identity_if_solo(group):
        return tensor
    raise RuntimeError(
        "eager cross-process all_reduce outside shard_map is not supported on "
        "TPU builds — wrap the step in fleet.distributed_step / shard_map")


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reference collective.py:516 — result only meaningful on dst; on TPU we
    produce it everywhere (SPMD) which is a superset of the contract."""
    return all_reduce(tensor, op, group, sync_op)


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Reference collective.py:352.  Inside shard_map: take src's shard."""
    group = group or _get_default_group()
    if _in_trace(tensor):
        src_local = group.get_group_rank(src) if src in group.ranks else src

        def f(t):
            # all-gather then select src's copy (XLA folds this efficiently);
            idx = jax.lax.axis_index(group.axis_name)
            gathered = jax.lax.all_gather(t, group.axis_name)
            return gathered[src_local]
        out = apply(f, tensor)
        if isinstance(tensor, Tensor):
            tensor._adopt(out)
            return tensor
        return out
    if _identity_if_solo(group):
        return tensor
    raise RuntimeError("eager cross-process broadcast requires shard_map context")


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    """Reference collective.py:618 — appends per-rank tensors to tensor_list."""
    group = group or _get_default_group()
    if _in_trace(tensor):
        out = apply(lambda t: jax.lax.all_gather(t, group.axis_name), tensor)
        if tensor_list is not None:
            for i in range(group.nranks):
                tensor_list.append(out[i])
            return tensor_list
        return out
    if _identity_if_solo(group):
        if tensor_list is not None:
            tensor_list.append(tensor)
            return tensor_list
        return tensor
    raise RuntimeError("eager cross-process all_gather requires shard_map context")


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)
    return object_list


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True, input_tensor=None):
    group = group or _get_default_group()
    src = input_tensor if input_tensor is not None else (
        tensor_list if tensor_list is not None else tensor)
    if _in_trace(src if not isinstance(src, list) else src[0]):
        def f(t):
            if isinstance(t, (list, tuple)):
                t = jnp.stack(t, 0).reshape((-1,) + tuple(jnp.shape(t[0])[1:]))
            return jax.lax.psum_scatter(t, group.axis_name, scatter_dimension=0,
                                        tiled=True)
        out = apply(f, src)
        if isinstance(tensor, Tensor) and tensor is not src:
            tensor._adopt(out)
            return tensor
        return out
    if _identity_if_solo(group):
        return src
    raise RuntimeError("eager cross-process reduce_scatter requires shard_map")


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Reference collective.py:704."""
    group = group or _get_default_group()
    if tensor_list is not None and _in_trace(tensor_list[0] if tensor_list else tensor):
        def f(ts):
            stacked = jnp.stack(ts, 0)
            idx = jax.lax.axis_index(group.axis_name)
            # every rank stacks the same (src) list; pick own slice
            return stacked[idx]
        out = apply(f, list(tensor_list))
        if isinstance(tensor, Tensor):
            tensor._adopt(out)
            return tensor
        return out
    if _identity_if_solo(group):
        if tensor_list:
            t0 = tensor_list[0]
            if isinstance(tensor, Tensor):
                tensor._adopt(t0 if isinstance(t0, Tensor) else Tensor(t0))
                return tensor
            return t0
        return tensor
    raise RuntimeError("eager cross-process scatter requires shard_map")


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """Reference collective.py:1488 — the MoE workhorse."""
    group = group or _get_default_group()
    first = in_tensor_list[0] if isinstance(in_tensor_list, (list, tuple)) \
        else in_tensor_list
    if _in_trace(first):
        def f(ts):
            x = jnp.stack(ts, 0) if isinstance(ts, (list, tuple)) else ts
            return jax.lax.all_to_all(x, group.axis_name, split_axis=0,
                                      concat_axis=0, tiled=False)
        out = apply(f, list(in_tensor_list) if isinstance(in_tensor_list,
                                                          (list, tuple))
                    else in_tensor_list)
        if out_tensor_list is not None:
            for i in range(group.nranks):
                out_tensor_list.append(out[i])
            return out_tensor_list
        return out
    if _identity_if_solo(group):
        if out_tensor_list is not None:
            out_tensor_list.extend(in_tensor_list)
            return out_tensor_list
        return in_tensor_list
    raise RuntimeError("eager cross-process alltoall requires shard_map")


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    group = group or _get_default_group()
    if _in_trace(in_tensor):
        out = apply(lambda t: jax.lax.all_to_all(
            t.reshape((group.nranks, -1) + tuple(jnp.shape(t)[1:]))
            if False else t.reshape((group.nranks, t.shape[0] // group.nranks)
                                    + tuple(t.shape[1:])),
            group.axis_name, split_axis=0, concat_axis=0,
            tiled=False).reshape(t.shape), in_tensor)
        if isinstance(out_tensor, Tensor):
            out_tensor._adopt(out)
            return out_tensor
        return out
    if _identity_if_solo(group):
        return in_tensor
    raise RuntimeError("eager cross-process alltoall_single requires shard_map")


def send(tensor, dst=0, group=None, sync_op=True):
    """Reference collective.py:1573.  Inside shard_map this becomes a
    ppermute shifting data to ``dst`` along the group axis (paired with the
    receiver's recv — see p2p in fleet.meta_parallel)."""
    group = group or _get_default_group()
    if _in_trace(tensor):
        src = group.rank if group.rank >= 0 else 0
        perm = [(src, group.get_group_rank(dst) if dst in group.ranks else dst)]
        return apply(lambda t: jax.lax.ppermute(t, group.axis_name, perm), tensor)
    if _identity_if_solo(group):
        return tensor
    raise RuntimeError("eager cross-process send requires shard_map context")


def recv(tensor, src=0, group=None, sync_op=True):
    group = group or _get_default_group()
    if _in_trace(tensor):
        dst = group.rank if group.rank >= 0 else 0
        perm = [(group.get_group_rank(src) if src in group.ranks else src, dst)]
        out = apply(lambda t: jax.lax.ppermute(t, group.axis_name, perm), tensor)
        if isinstance(tensor, Tensor):
            tensor._adopt(out)
            return tensor
        return out
    if _identity_if_solo(group):
        return tensor
    raise RuntimeError("eager cross-process recv requires shard_map context")


isend = send
irecv = recv


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Megatron-style declarative sharded Embedding/Linear
    (reference collective.py:1276 ``split``).  Returns the layer output with
    row/col-parallel layout handled by the fleet TP layers."""
    from .fleet.meta_parallel.parallel_layers.mp_layers import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr)
        return layer(x)
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                      has_bias=bias_attr is not False,
                                      input_is_parallel=False)
        else:
            layer = ColumnParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation}")
