"""Elastic training manager.

Reference: fleet/elastic/manager.py:130 ``ElasticManager`` — etcd-backed host
registration, heartbeat lease (:250), node-change watch (:234), two levels
(fault-tolerant restart vs true scale-in/out :178), exit-code protocol
(101 restart, 102 rescale).

TPU-native: membership lives in a pluggable store (distributed/store.py) —
either a shared-filesystem directory (GCS fuse / NFS, the TPU-pod deployment
shape) or the native TCP store (csrc/kv_store.cpp) at ``tcp://master:port``,
which spans hosts with no shared mount or etcd.  A scale event
maps to *checkpoint → exit(101) → relaunch → re-compile with the new mesh*,
because XLA programs are specialized on mesh shape (re-compile ≙ the
reference's program re-build after env rewrite).  The launcher
(distributed/launch.py) honors the same exit codes.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, List, Optional

from ..store import make_store

ELASTIC_EXIT_CODE = 101      # relaunch with same world
RESCALE_EXIT_CODE = 102      # relaunch with new world size

ElasticLevel = type("ElasticLevel", (), {"FAULT_TOLERANCE": 1, "ELASTIC": 2})


def read_alive_ranks(store_target, ttl: float,
                     now: Optional[float] = None) -> List[int]:
    """Ranks with a fresh heartbeat lease (shared between ElasticManager and
    the launcher so membership logic cannot drift).  ``store_target``: a
    directory, a ``tcp://`` URL, or an already-constructed store object."""
    now = time.time() if now is None else now
    out = []
    own_store = isinstance(store_target, str)
    try:
        store = make_store(store_target, timeout=5.0) if own_store \
            else store_target
    except Exception:
        return out  # store unreachable ⇒ "nobody visible" (degrade, not die)
    try:
        entries = store.list_prefix("host-")
    except Exception:
        # transient store outage degrades to "nobody visible" — the caller's
        # too-few-alive path then checkpoints and exits 101 (same behavior the
        # file backend had when the dir was unreadable)
        return out
    finally:
        if own_store:
            store.close()
    for key, raw in entries.items():
        if not key.endswith(".json"):
            continue
        try:
            rec = json.loads(raw.decode())
            if now - rec["ts"] <= ttl:
                out.append(int(rec["rank"]))
        except (ValueError, KeyError):
            continue
    return sorted(out)


class ElasticManager:
    """File-store membership + heartbeat; decides when the world changed."""

    def __init__(self, store_dir: str, rank: Optional[int] = None,
                 np_range: str = "", heartbeat_interval: float = 2.0,
                 lease_ttl: float = 10.0):
        from .. import env
        self.store_dir = store_dir
        self.store = make_store(store_dir)  # dir or tcp://host:port
        self.rank = env.get_rank() if rank is None else rank
        self.interval = heartbeat_interval
        self.ttl = lease_ttl
        lo, _, hi = str(np_range).partition(":")
        self.np_min = int(lo) if lo else 1
        self.np_max = int(hi) if hi else max(self.np_min, env.get_world_size())
        self.elastic_level = (ElasticLevel.ELASTIC if hi
                              else ElasticLevel.FAULT_TOLERANCE)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._world_at_start: Optional[List[int]] = None

    # ------------------------------------------------------------ membership
    def _hb_key(self, rank: int) -> str:
        return f"host-{rank}.json"

    def register(self):
        """Write this host's heartbeat file and start the lease thread
        (≙ manager.py:250 heartbeat lease).  The membership baseline is NOT
        taken here — peers may still be joining; it is snapshotted on the
        first ``exit_code()`` check (i.e. when training actually starts) or
        explicitly via ``refresh_world()``."""
        self._beat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def refresh_world(self):
        """Re-baseline membership (call after a rescale/restart completes)."""
        self._world_at_start = self.alive_ranks()
        return self._world_at_start

    def _beat(self):
        rec = json.dumps({"rank": self.rank, "ts": time.time()})
        self.store.set(self._hb_key(self.rank), rec.encode())

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self._beat()  # transient store outage must not kill the lease
            except Exception:
                continue      # TCPStore redials on the next attempt

    def alive_ranks(self, now: Optional[float] = None) -> List[int]:
        return read_alive_ranks(self.store, self.ttl, now)

    # ------------------------------------------------------------- decisions
    def world_changed(self) -> bool:
        if self._world_at_start is None:
            self.refresh_world()
        return self.alive_ranks() != self._world_at_start

    def exit_code(self) -> Optional[int]:
        """None = keep training; 101 = restart same world (a peer bounced);
        102 = rescale (world grew/shrank within [np_min, np_max])."""
        if self._world_at_start is None:
            self.refresh_world()
        alive = self.alive_ranks()
        if alive == self._world_at_start:
            return None
        if len(alive) < self.np_min:
            return ELASTIC_EXIT_CODE  # too few — wait-and-restart
        if self.elastic_level == ElasticLevel.ELASTIC and \
                len(alive) != len(self._world_at_start):
            return RESCALE_EXIT_CODE
        return ELASTIC_EXIT_CODE

    def run_with_checkpoint(self, train_fn: Callable[[], None],
                            save_fn: Optional[Callable[[], None]] = None,
                            check_every: float = 5.0, manager=None,
                            state_fn: Optional[Callable[[], object]] = None,
                            step_fn: Optional[Callable[[], int]] = None,
                            deadline_s: Optional[float] = None):
        """Drive ``train_fn`` (which returns per 'epoch'); on membership
        change, save and exit with the protocol code so the launcher
        relaunches and the job resumes from checkpoint with a freshly
        compiled mesh.

        Two save paths: a bare ``save_fn`` callback (legacy), or
        ``manager=`` (a ``train_resilience.CheckpointManager``) with
        ``state_fn``/``step_fn`` providers — the rescale save then rides
        the verified two-phase commit (digest manifest + COMMIT marker),
        so the relaunched world resumes through ``latest()`` and
        reshards via the current ``sharding_rules``.  ``deadline_s``
        bounds the emergency save the same way the preemption path does
        (a miss abandons uncommitted; the prior step stays valid)."""
        import sys
        if save_fn is None:
            if manager is None or state_fn is None or step_fn is None:
                raise ValueError(
                    "run_with_checkpoint needs save_fn, or manager= with "
                    "state_fn=/step_fn= for the managed two-phase path")

            def save_fn():
                manager.save(state_fn(), step_fn(),
                             deadline_s=deadline_s).wait()
        last = time.time()
        while True:
            more = train_fn()
            if time.time() - last >= check_every:
                last = time.time()
                code = self.exit_code()
                if code is not None:
                    save_fn()
                    sys.exit(code)
            if not more:
                return

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 2)
        try:
            self.store.delete(self._hb_key(self.rank))
        except OSError:
            pass
