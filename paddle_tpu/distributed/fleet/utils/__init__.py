"""fleet.utils (reference: fleet/utils/ — recompute.py:63, hybrid_parallel_util.py)."""

from __future__ import annotations

import jax

from ....core import rng
from ....core.tensor import Tensor, apply


def recompute(function, *args, **kwargs):
    """Activation recomputation (reference fleet/utils/recompute.py:63
    RecomputeFunction PyLayer with RNG-state replay).

    TPU-native: ``jax.checkpoint`` — XLA rematerializes the segment in
    backward; RNG replay is automatic because draws derive from the traced
    scope key.  In eager mode the tape already recomputes forward per-node
    vjp, so this is the identity there."""
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    leaves = jax.tree_util.tree_leaves(list(args), is_leaf=lambda x: isinstance(x, Tensor))
    traced = any(isinstance(getattr(l, "_data", l), jax.core.Tracer) for l in leaves)
    if traced:
        def pure(*raw):
            from ....jit.functional import wrap_tree, unwrap_tree
            return unwrap_tree(function(*wrap_tree(list(raw)), **kwargs))
        from ....jit.functional import unwrap_tree, wrap_tree
        out = jax.checkpoint(pure)(*unwrap_tree(list(args)))
        return wrap_tree(out)
    return function(*args, **kwargs)


def fused_allreduce_gradients(parameter_list, hcg):
    """Reference fleet/utils/hybrid_parallel_util.py:117 — DP grad fusion +
    allreduce.  On TPU, DP gradients are reduced by GSPMD (batch sharded on
    the "data" axis); eager single-process is a no-op.  Kept for API parity."""
    return None


def broadcast_mp_parameters(model, hcg):
    return None


def broadcast_dp_parameters(model, hcg):
    return None


def broadcast_sharding_parameters(model, hcg):
    return None


class UtilBase:
    """Fleet util surface (reference fleet/base/util_factory.py UtilBase):
    collective helpers + filesystem passthroughs."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        from ..metrics.metric import _allreduce
        import numpy as np
        return _allreduce(np.asarray(input), mode)

    def barrier(self, comm_world="worker"):
        from ...collective import barrier as _barrier
        _barrier()

    def all_gather(self, input, comm_world="worker"):
        from ...collective import all_gather_object
        out = []
        all_gather_object(out, input)
        return out

    def get_file_shard(self, files):
        """Split a file list over workers with the remainder spread one file
        at a time (util_factory.py: shard sizes differ by at most 1 — a
        ceil-sized contiguous split would hand trailing workers ZERO files
        and deadlock them at the first collective)."""
        from .. import fleet
        n = fleet.worker_num()
        i = fleet.worker_index()
        base, rem = divmod(len(files), n)
        start = i * base + min(i, rem)
        return files[start:start + base + (1 if i < rem else 0)]

    def print_on_rank(self, message, rank_id=0):
        from .. import fleet
        if fleet.worker_index() == rank_id:
            print(message)
