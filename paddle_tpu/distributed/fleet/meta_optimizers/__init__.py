from .hybrid_optimizer import HybridParallelOptimizer  # noqa: F401
