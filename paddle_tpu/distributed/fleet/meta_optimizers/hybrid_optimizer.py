"""HybridParallelOptimizer facade.

Reference: fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:173
— wraps the inner optimizer, fuses DP allreduce (fused_allreduce_gradients),
re-scopes global-norm clip to psum over model-parallel axes
(HybridParallelClipGrad).

TPU: the DP allreduce is implicit in batch sharding; what remains is (a) the
eager facade API, (b) clip re-scoping, which we implement by injecting mesh
axes into ClipGradByGlobalNorm when used inside shard_map.
"""

from __future__ import annotations


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        clip = getattr(optimizer, "_grad_clip", None)
        if clip is not None and hasattr(clip, "axes") and hcg is not None:
            axes = []
            if hcg.get_model_parallel_world_size() > 1:
                axes.append("model")
            if hcg.get_pipe_parallel_world_size() > 1:
                axes.append("pipe")
            clip.axes = axes or None

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters,
                                        no_grad_set)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
