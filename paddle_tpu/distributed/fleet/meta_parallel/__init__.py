"""Hybrid-parallel model wrappers.

Reference: fleet/meta_parallel/{data_parallel → dygraph/parallel.py:397,
tensor_parallel.py:25, pipeline_parallel.py:30, sharding_parallel.py:23}.

On TPU the wrappers do not install gradient hooks or comm groups — they tag
the model with the parallel mode and delegate the actual distribution to the
SPMD step builder (spmd.py).  API surface (train_batch etc.) is preserved.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ....core import rng
from ....core.tensor import Tensor
from ....nn.layer.base import Layer
from ...topology import get_hybrid_communicate_group
from .parallel_layers.mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,  # noqa: F401
                                        RowParallelLinear, VocabParallelEmbedding)
from .parallel_layers.pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .parallel_layers.random import RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed  # noqa: F401


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        self._strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    @property
    def parameters(self):
        return self._layers.parameters


class DataParallel(MetaParallelBase):
    """Reference: fluid/dygraph/parallel.py:397 — on TPU, gradient sync is a
    consequence of batch sharding on the "data" mesh axis; no Reducer."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__(layers, None, strategy)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass


class TensorParallel(MetaParallelBase):
    """Reference: tensor_parallel.py:25 — broadcast of inputs/params across
    the mp group is subsumed by replicated sharding."""


class ShardingParallel(MetaParallelBase):
    """Reference: sharding_parallel.py:23."""


class PipelineParallel(MetaParallelBase):
    """Reference: pipeline_parallel.py:30 (train_batch:152,
    forward_backward_pipeline:80 1F1B).

    TPU engine: the step is ONE jit containing a shard_map micro-batch loop
    over the "pipe" axis (spmd.spmd_pipeline).  ``train_batch`` keeps the
    reference's signature: feed a global batch; it is split into
    ``accumulate_steps`` micro-batches inside the compiled program.
    """

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__(layers, hcg, strategy)
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", 1)
        self._step_fn = None
        self._state = None
        self._optimizer = None
        self._loss_fn = None

    def _ensure_step(self, optimizer, loss_fn):
        if self._step_fn is None:
            from ...pipeline_engine import make_pipeline_train_step
            self._optimizer = optimizer
            self._loss_fn = loss_fn
            self._step_fn, self._state = make_pipeline_train_step(
                self._layers, loss_fn, optimizer, self._hcg,
                self.accumulate_steps)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        inputs, labels = data
        self._ensure_step(optimizer, self._layers._loss_fn)
        key = rng.next_key()
        lr = np.float32(optimizer.get_lr())
        raw_in = getattr(inputs, "_data", inputs)
        raw_lab = getattr(labels, "_data", labels)
        self._state, loss = self._step_fn(self._state, key, lr, raw_in, raw_lab)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(loss)

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out
