"""Pipeline layer description & segmentation.

Reference: fleet/meta_parallel/parallel_layers/pp_layers.py — LayerDesc:31,
SharedLayerDesc:49, SegmentLayers:63 (uniform or param-weighted),
PipelineLayer:132.

TPU-native: PipelineLayer keeps the reference's description API (the user
declares the full model as a list of LayerDescs) but materializes it in one
of two forms:
- local stage layers (reference behavior) for the shard_map pipeline engine;
- a stage-stacked pytree (same structure per stage) for the scan-over-stages
  fast path when all stages are isomorphic.
"""

from __future__ import annotations

import math
import re
from typing import Callable, List, Optional

import numpy as np

from .....nn.layer.base import Layer
from .....nn.layer.containers import LayerList
from ....topology import get_hybrid_communicate_group


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("layer_cls must be a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight shared between stages (e.g. embedding/lm-head tying).

    Reference pp_layers.py:49 — builds comm groups to sync the shared weight;
    on TPU the shared weight is simply the SAME pytree entry referenced by
    both stages (replication handled by sharding)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Reference pp_layers.py:63 — split N layer descs into num_parts."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method
        if len(layers_desc) < num_parts:
            raise ValueError("too few layers for the number of pipeline stages")

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self.uniform(len(self.descs), self.num_parts)
        if self.method.startswith("layer:"):
            # segment by counting occurrences of a named layer class
            name = self.method.split(":", 1)[1]
            weights = [1 if re.search(name, type_name(d)) else 0 for d in self.descs]
            return self.segment_by_weight(weights)
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0]
        base = num_items // num_parts
        extra = num_items % num_parts
        for i in range(num_parts):
            result.append(result[-1] + base + (1 if i >= num_parts - extra else 0))
        return result

    def segment_by_weight(self, weights):
        total = sum(weights)
        per = total / self.num_parts
        result = [0]
        acc = 0
        part = 1
        for i, w in enumerate(weights):
            acc += w
            if acc >= per * part and part < self.num_parts:
                result.append(i + 1)
                part += 1
        result.append(len(weights))
        while len(result) < self.num_parts + 1:
            result.append(len(weights))
        return result


def type_name(d):
    if isinstance(d, LayerDesc):
        return d.layer_cls.__name__
    return type(d).__name__


class PipelineLayer(Layer):
    """Reference pp_layers.py:132.

    When pp_degree == 1 this is just a Sequential over the full desc list.
    With pp > 1, builds per-stage sublayers; ``stage_fn(stage_id)`` returns a
    callable for the shard_map pipeline engine, and segmentation follows
    ``seg_method``.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        hcg = get_hybrid_communicate_group()
        if num_stages is None and hcg is not None:
            num_stages = hcg.get_pipe_parallel_world_size()
        self._num_stages = num_stages or 1
        self._stage_id = hcg.get_stage_id() if hcg is not None else 0
        seg = SegmentLayers(self._layers_desc, self._num_stages, seg_method)
        self.segment_parts = seg.do_segment()
        # On TPU every process materializes ALL stages (SPMD single-program);
        # the sharding pass places each stage's params on its pipe coordinate.
        self._stage_layers: List[LayerList] = []
        self._shared = {}
        run_all = LayerList()
        for stage in range(self._num_stages):
            lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
            stage_list = LayerList()
            for i in range(lo, hi):
                desc = self._layers_desc[i]
                if isinstance(desc, SharedLayerDesc):
                    if desc.layer_name not in self._shared:
                        self._shared[desc.layer_name] = (desc.build_layer(), desc)
                    layer, _ = self._shared[desc.layer_name]
                elif isinstance(desc, LayerDesc):
                    layer = desc.build_layer()
                else:
                    layer = desc  # already a Layer (or function)
                stage_list.append(layer) if isinstance(layer, Layer) else None
                run_all.append(layer) if isinstance(layer, Layer) else None
            self._stage_layers.append(stage_list)
        self.add_sublayer("stages", LayerList(
            [l for sl in self._stage_layers for l in sl]))
        # mark each parameter with its pipeline stage for the sharding pass
        for stage in range(self._num_stages):
            for layer in self._stage_layers[stage]:
                for _, p in layer.named_parameters():
                    if not hasattr(p, "_pp_stage"):
                        p._pp_stage = stage

    def get_num_stages(self):
        return self._num_stages

    @property
    def parameters_desc(self):
        return self._layers_desc

    def get_stage_from_index(self, layer_idx):
        for stage in range(self._num_stages):
            if self.segment_parts[stage] <= layer_idx < self.segment_parts[stage + 1]:
                return stage
        return self._num_stages - 1

    def stage_layers(self, stage_id):
        return self._stage_layers[stage_id]

    def forward(self, input, chunk_id=None):
        """Full serial forward (single-program semantics; the pipeline engine
        overrides execution with the shard_map schedule)."""
        x = input
        for stage_list in self._stage_layers:
            for layer in stage_list:
                x = layer(x)
        return x

    def forward_stage(self, x, stage_id):
        for layer in self._stage_layers[stage_id]:
            x = layer(x)
        return x
