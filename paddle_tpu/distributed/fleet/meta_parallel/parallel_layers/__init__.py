from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,  # noqa: F401
                        RowParallelLinear, VocabParallelEmbedding)
from .pp_layers import LayerDesc, PipelineLayer, SegmentLayers, SharedLayerDesc  # noqa: F401
from .random import RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed  # noqa: F401
