"""Model-parallel RNG trackers (reference: parallel_layers/random.py —
local vs global seed streams so dropout differs across TP ranks)."""

from __future__ import annotations

from .....core.rng import RNGSequenceTracker, get_rng_state_tracker as _core_tracker

MODEL_PARALLEL_RNG = "model_parallel_rng"

RNGStatesTracker = RNGSequenceTracker


def get_rng_state_tracker() -> RNGSequenceTracker:
    return _core_tracker()


def model_parallel_random_seed(seed: int = None):
    import random as pyrandom
    from .... import env
    rank = env.get_rank()
    if seed is None:
        seed = pyrandom.randint(0, 100000)
    global_seed = seed
    local_seed = seed + 1024 + rank
    tracker = get_rng_state_tracker()
    tracker.seeds.pop(MODEL_PARALLEL_RNG, None)
    tracker.add(MODEL_PARALLEL_RNG, local_seed)
    from .....core import rng as core_rng
    core_rng.seed(global_seed)
