"""Model-parallel RNG trackers (reference: parallel_layers/random.py —
local vs global seed streams so dropout differs across TP ranks)."""

from __future__ import annotations

from .....core.rng import RNGSequenceTracker, get_rng_state_tracker as _core_tracker

MODEL_PARALLEL_RNG = "model_parallel_rng"

RNGStatesTracker = RNGSequenceTracker


def get_rng_state_tracker() -> RNGSequenceTracker:
    return _core_tracker()


def model_parallel_random_seed(seed: int = None):
    """Seed the MP tracker from ``(global seed, mp rank)``.

    ``seed=None`` derives from the process-wide ``FLAGS_seed`` instead of an
    unseeded ``random.randint`` — every host must compute the SAME global
    seed or dropout masks diverge across model-parallel replicas and the
    sharded forward silently stops matching the single-host one (tpulint
    rule ``unseeded-nondeterminism``; this was its founding true-positive).
    """
    from .... import env
    from .....core import flags
    rank = env.get_rank()
    if seed is None:
        seed = int(flags.flag("FLAGS_seed"))
    global_seed = seed
    local_seed = seed + 1024 + rank
    tracker = get_rng_state_tracker()
    tracker.seeds.pop(MODEL_PARALLEL_RNG, None)
    tracker.add(MODEL_PARALLEL_RNG, local_seed)
    from .....core import rng as core_rng
    core_rng.seed(global_seed)
