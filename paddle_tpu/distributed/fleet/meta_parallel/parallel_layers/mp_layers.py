"""Tensor (model) parallel layers.

Reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
mp_layers.py — VocabParallelEmbedding:30, ColumnParallelLinear:97,
RowParallelLinear:170, ParallelCrossEntropy:249 (c_softmax_with_cross_entropy).

TPU-native dual mode:
- **GSPMD mode** (under ``pjit``, the default fleet path): layers hold the
  FULL logical weight annotated with a dims_mapping (weight._dims_mapping =
  {dim: "model"}); the fleet step shards them via NamedSharding and XLA
  inserts the collectives.  The explicit allreduce of the reference becomes
  a sharding constraint.
- **shard_map mode** (explicit SPMD, used by the pipeline engine and tests):
  when the "model" axis is in scope, layers hold 1/mp of the weight and issue
  ``lax.psum`` exactly like the reference's c_allreduce.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor, apply
from .....nn import functional as F
from .....nn.initializer import Constant, XavierUniform
from .....nn.layer.base import Layer
from ....topology import get_hybrid_communicate_group


def _mp_info():
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return 1, "model"
    return hcg.get_model_parallel_world_size(), hcg.axis_name("mp")


from ....collective import _axis_in_scope  # noqa: E402 — single shared impl


class VocabParallelEmbedding(Layer):
    """Vocab-sharded embedding.  GSPMD: weight sharded on dim 0 over "model"."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight._dims_mapping = {0: "model"}

    def forward(self, x):
        mp, axis = _mp_info()
        if mp > 1 and _axis_in_scope(axis):
            # explicit SPMD: local shard covers [rank*per, (rank+1)*per)
            def f(i, w):
                per = w.shape[0]
                rank = jax.lax.axis_index(axis)
                lo = rank * per
                local = i - lo
                valid = (local >= 0) & (local < per)
                emb = jnp.take(w, jnp.clip(local, 0, per - 1), axis=0)
                emb = jnp.where(valid[..., None], emb, 0.0)
                return jax.lax.psum(emb, axis)
            return apply(f, x, self.weight)
        out = F.embedding(x, self.weight)
        return out


class ColumnParallelLinear(Layer):
    """Weight (in, out) sharded on the OUT dim over "model"."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features, self._out_features = in_features, out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr,
                                            default_initializer=XavierUniform())
        self.weight._dims_mapping = {1: "model"}
        self.weight.is_distributed = True
        if has_bias is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([out_features], is_bias=True,
                                              default_initializer=Constant(0.0))
            self.bias._dims_mapping = {0: "model"}
            self.bias.is_distributed = True

    def forward(self, x):
        mp, axis = _mp_info()
        out = F.linear(x, self.weight, self.bias)
        if mp > 1 and _axis_in_scope(axis) and self.gather_output:
            out = apply(lambda t: jnp.moveaxis(
                jax.lax.all_gather(t, axis), 0, -2).reshape(t.shape[:-1] + (-1,)), out)
        return out


class RowParallelLinear(Layer):
    """Weight (in, out) sharded on the IN dim over "model"; output psum."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self._in_features, self._out_features = in_features, out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr,
                                            default_initializer=XavierUniform())
        self.weight._dims_mapping = {0: "model"}
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True,
                                              default_initializer=Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        mp, axis = _mp_info()
        if mp > 1 and _axis_in_scope(axis):
            def f(a, w, b):
                if not self.input_is_parallel:
                    # split input's last dim to this rank's shard
                    per = w.shape[0]
                    rank = jax.lax.axis_index(axis)
                    a = jax.lax.dynamic_slice_in_dim(a, rank * per, per, axis=-1)
                out = a @ w
                out = jax.lax.psum(out, axis)
                if b is not None:
                    out = out + b
                return out
            return apply(f, x, self.weight, self.bias)
        return F.linear(x, self.weight, self.bias)


class ParallelCrossEntropy(Layer):
    """Vocab-sharded softmax-cross-entropy (reference mp_layers.py:249 →
    c_softmax_with_cross_entropy_op.cu): logits sharded on the class dim;
    max/sum/target-logit psum'd over the model axis."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        mp, axis = _mp_info()
        if mp > 1 and _axis_in_scope(axis):
            def f(logits, lab):
                per = logits.shape[-1]
                rank = jax.lax.axis_index(axis)
                lo = rank * per
                gmax = jax.lax.pmax(jnp.max(logits, -1, keepdims=True), axis)
                ex = jnp.exp(logits - gmax)
                denom = jax.lax.psum(jnp.sum(ex, -1, keepdims=True), axis)
                local = lab - lo
                valid = (local >= 0) & (local < per)
                tgt = jnp.take_along_axis(
                    logits, jnp.clip(local, 0, per - 1)[..., None], axis=-1)[..., 0]
                tgt = jnp.where(valid, tgt, 0.0)
                tgt = jax.lax.psum(tgt, axis)
                loss = jnp.log(denom[..., 0]) + gmax[..., 0] - tgt
                return loss[..., None]
            return apply(f, input, label)
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
