"""``paddle_tpu.distributed.fleet`` (reference: fleet/base/fleet_base.py —
Fleet:170 init, distributed_optimizer:829, distributed_model:882).

The Fleet singleton wires: DistributedStrategy → HybridCommunicateGroup
(mesh) → SPMD step builders.  Meta-optimizer selection/program-rewrite
(fleet_base.py:1432 + strategy_compiler.py) is replaced by sharding rules.
"""

from __future__ import annotations

from typing import Optional

from ...core.tensor import Tensor
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        get_hybrid_communicate_group,
                        set_hybrid_communicate_group)
from .base.distributed_strategy import DistributedStrategy
from .base.role_maker import (PaddleCloudRoleMaker, Role, RoleMakerBase,  # noqa: F401
                              UserDefinedRoleMaker)
from . import meta_parallel  # noqa: F401
from .meta_parallel import (DataParallel, PipelineParallel, ShardingParallel,  # noqa: F401
                            TensorParallel)


class _RoleMaker(PaddleCloudRoleMaker):
    """Default role maker: PaddleCloud env contract, with jax process info
    as the fallback when no scheduler env is present (collective/worker
    path only — PS-mode server identity from super() is kept as computed)."""

    def _generate_role(self):
        super()._generate_role()
        import os
        if self._role == Role.WORKER and \
                "PADDLE_TRAINER_ENDPOINTS" not in os.environ and \
                "PADDLE_TRAINERS_NUM" not in os.environ:
            from .. import env
            self._worker_endpoints = [f"process:{i}"
                                      for i in range(env.get_world_size())]
            self._current_id = env.get_rank()


class Fleet:
    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._role_maker = None
        self._user_defined_optimizer = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective: bool = True, strategy=None,
             log_level="INFO"):
        from .. import env
        env.init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        self._role_maker = role_maker or _RoleMaker(is_collective)
        hc = self._strategy.hybrid_configs
        self._hcg = HybridCommunicateGroup(
            dp_degree=hc.get("dp_degree", 1), mp_degree=hc.get("mp_degree", 1),
            pp_degree=hc.get("pp_degree", 1),
            sharding_degree=hc.get("sharding_degree", 1),
            sep_degree=hc.get("sep_degree", 1),
            virtual_pp_degree=hc.get("pp_configs", {})
                                .get("virtual_pipeline_degree", 1))
        set_hybrid_communicate_group(self._hcg)
        self._is_initialized = True
        return self

    # ------------------------------------------------------------- topology
    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        return self._hcg

    def worker_num(self):
        return self._role_maker._worker_num()

    def worker_index(self):
        return self._role_maker._worker_index()

    def is_first_worker(self):
        return self.worker_index() == 0

    def is_worker(self):
        return self._role_maker._is_worker()

    def is_server(self):
        return self._role_maker._is_server()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker._get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_endpoints(self, to_string=False):
        eps = self._role_maker._get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    def server_num(self):
        return self._role_maker._server_num()

    def server_index(self):
        return self._role_maker._server_index()

    # reference aliases: rank/nranks/world_size over the worker axis
    def rank(self):
        return self.worker_index()

    def nranks(self):
        return self.worker_num()

    def world_size(self):
        return self.worker_num()

    def local_rank(self):
        """Rank within this node (workers are laid out node-major)."""
        per_node = max(1, self.worker_num() // max(1, self.node_num()))
        return self.worker_index() % per_node

    def local_device_ids(self):
        import jax
        return list(range(jax.local_device_count()))

    def world_device_ids(self):
        import jax
        return list(range(jax.device_count()))

    def node_num(self):
        import jax
        return jax.process_count()

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    @property
    def util(self):
        """Reference fleet.util surface (util_factory.py)."""
        if getattr(self, "_util", None) is None:
            from .utils import UtilBase
            self._util = UtilBase()
        return self._util

    # -------------------------------------------- PS lifecycle (non-goal)
    def init_worker(self):
        """PS worker bootstrap — collective-only build (SURVEY §7 declares
        the parameter-server runtime a non-goal); nothing to start."""

    def init_server(self, *args, **kwargs):
        raise RuntimeError(
            "the parameter-server runtime is a declared non-goal of this "
            "TPU build (SURVEY §7); use collective mode")

    run_server = init_server

    def stop_worker(self):
        pass

    def shrink(self, threshold=None):
        raise RuntimeError("PS sparse-table shrink is a parameter-server "
                           "feature; not available in the collective build")

    # -------------------------------- optimizer passthroughs (fleet_base)
    def _opt(self):
        if self._user_defined_optimizer is None:
            raise RuntimeError("call fleet.distributed_optimizer(...) first")
        return self._user_defined_optimizer

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._opt().minimize(loss)

    def step(self):
        return self._opt().step()

    def clear_grad(self):
        return self._opt().clear_grad()

    def get_lr(self):
        return self._opt().get_lr()

    def set_lr(self, value):
        return self._opt().set_lr(value)

    def state_dict(self):
        return self._opt().state_dict()

    def set_state_dict(self, state_dict):
        return self._opt().set_state_dict(state_dict)

    # ------------------------------------------------------------ model io
    def save_inference_model(self, executor, dirname, feeded_var_names=None,
                             target_vars=None, main_program=None, **kwargs):
        """First-worker-only export through the jit/StableHLO path.  The
        exportable object (a Layer / traced program) comes from
        ``main_program`` (or a ``program=`` kwarg)."""
        if not self.is_first_worker():
            return
        program = main_program or kwargs.pop("program", None)
        if program is None:
            raise ValueError(
                "save_inference_model needs the layer/program to export: "
                "pass main_program= (a Layer or StaticFunction)")
        from ... import static as _static
        return _static.save_inference_model(dirname, feeded_var_names,
                                            target_vars, executor,
                                            program=program, **kwargs)

    def save_persistables(self, executor, dirname, main_program=None, mode=0):
        if not self.is_first_worker():
            return
        from ...framework import io as _io
        from ... import static as _static
        prog = main_program or _static.default_main_program()
        params = dict(getattr(prog, "_params", {}) or {})
        if not params and hasattr(prog, "named_parameters"):
            params = {n: p for n, p in prog.named_parameters()}
        if not params:
            raise ValueError(
                "no parameters found to persist: pass main_program= (a "
                "Layer, or a Program populated via static.create_parameter)")
        _io.save(params, dirname if dirname.endswith(".pdparams")
                 else dirname + "/persistables.pdparams")

    def load_model(self, path, mode=0):
        from ...framework import io as _io
        return _io.load(path if path.endswith(".pdparams")
                        else path + "/persistables.pdparams")

    # ------------------------------------------------------------ wrapping
    def distributed_model(self, model):
        """Reference fleet_base.py:882 — wrap by parallel mode."""
        mode = self._hcg.get_parallel_mode()
        from .meta_parallel.parallel_layers.pp_layers import PipelineLayer
        if isinstance(model, PipelineLayer) or mode == "PipelineParallel":
            return PipelineParallel(model, self._hcg, self._strategy)
        if mode == "TensorParallel":
            return TensorParallel(model, self._hcg, self._strategy)
        if mode == "ShardingParallel":
            return ShardingParallel(model, self._hcg, self._strategy)
        if mode == "DataParallel":
            return DataParallel(model, self._strategy)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """Reference fleet_base.py:829 — returns a HybridParallelOptimizer
        facade; sharding/clip behavior is applied inside the SPMD step."""
        if strategy is not None:
            self._strategy = strategy
        self._user_defined_optimizer = optimizer
        from .meta_optimizers.hybrid_optimizer import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    def distributed_scaler(self, scaler):
        return scaler

    # ------------------------------------------------------- train builders
    def distributed_train_step(self, layer, loss_fn, optimizer):
        """TPU-native: build the jit hybrid step for (layer, loss, opt)."""
        from ..spmd import make_spmd_train_step
        zero = 0
        if self._strategy.sharding:
            zero = int(self._strategy.sharding_configs.get("stage", 1))
        acc = int(self._strategy.pipeline_configs.get("accumulate_steps", 1)) \
            if self._strategy.pipeline else 1
        inner = getattr(optimizer, "_inner_opt", optimizer)
        return make_spmd_train_step(layer, loss_fn, inner, self._hcg,
                                    zero_stage=zero, accumulate_steps=acc)


fleet = Fleet()

# module-level API (paddle.distributed.fleet.init style)
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
distributed_scaler = fleet.distributed_scaler
distributed_train_step = fleet.distributed_train_step
get_hybrid_communicate_group = lambda: fleet._hcg or get_hybrid_communicate_group()  # noqa: E731
worker_num = fleet.worker_num
worker_index = fleet.worker_index
is_first_worker = fleet.is_first_worker
is_worker = fleet.is_worker
is_server = fleet.is_server
worker_endpoints = fleet.worker_endpoints
server_endpoints = fleet.server_endpoints
server_num = fleet.server_num
server_index = fleet.server_index
rank = fleet.rank
nranks = fleet.nranks
world_size = fleet.world_size
local_rank = fleet.local_rank
local_device_ids = fleet.local_device_ids
world_device_ids = fleet.world_device_ids
node_num = fleet.node_num
barrier_worker = fleet.barrier_worker
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_worker = fleet.stop_worker
shrink = fleet.shrink
minimize = fleet.minimize
step = fleet.step
clear_grad = fleet.clear_grad
get_lr = fleet.get_lr
set_lr = fleet.set_lr
state_dict = fleet.state_dict
set_state_dict = fleet.set_state_dict
save_inference_model = fleet.save_inference_model
save_persistables = fleet.save_persistables
load_model = fleet.load_model

# dataset + util namespace parity
from ...io.dataset import (DatasetBase, InMemoryDataset,  # noqa: E402,F401
                           QueueDataset)
from .utils import UtilBase  # noqa: E402,F401
from .data_generator import (MultiSlotDataGenerator,  # noqa: E402,F401
                             MultiSlotStringDataGenerator)
from . import metrics  # noqa: E402,F401
util = fleet.util


class FileInstantDataset(QueueDataset):
    """Streaming per-file dataset (reference FileInstantDataset — the
    QueueDataset streaming semantics already match)."""


class BoxPSDataset:
    def __init__(self, *a, **k):
        raise RuntimeError("BoxPS is a GPU parameter-server feature; use "
                           "io.InMemoryDataset on TPU")

