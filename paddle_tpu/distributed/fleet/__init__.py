"""``paddle_tpu.distributed.fleet`` (reference: fleet/base/fleet_base.py —
Fleet:170 init, distributed_optimizer:829, distributed_model:882).

The Fleet singleton wires: DistributedStrategy → HybridCommunicateGroup
(mesh) → SPMD step builders.  Meta-optimizer selection/program-rewrite
(fleet_base.py:1432 + strategy_compiler.py) is replaced by sharding rules.
"""

from __future__ import annotations

from typing import Optional

from ...core.tensor import Tensor
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        get_hybrid_communicate_group,
                        set_hybrid_communicate_group)
from .base.distributed_strategy import DistributedStrategy
from .base.role_maker import (PaddleCloudRoleMaker, Role, RoleMakerBase,  # noqa: F401
                              UserDefinedRoleMaker)
from . import meta_parallel  # noqa: F401
from .meta_parallel import (DataParallel, PipelineParallel, ShardingParallel,  # noqa: F401
                            TensorParallel)


class _RoleMaker(PaddleCloudRoleMaker):
    """Default role maker: PaddleCloud env contract, with jax process info
    as the fallback when no scheduler env is present (collective/worker
    path only — PS-mode server identity from super() is kept as computed)."""

    def _generate_role(self):
        super()._generate_role()
        import os
        if self._role == Role.WORKER and \
                "PADDLE_TRAINER_ENDPOINTS" not in os.environ and \
                "PADDLE_TRAINERS_NUM" not in os.environ:
            from .. import env
            self._worker_endpoints = [f"process:{i}"
                                      for i in range(env.get_world_size())]
            self._current_id = env.get_rank()


class Fleet:
    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._role_maker = None
        self._user_defined_optimizer = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective: bool = True, strategy=None,
             log_level="INFO"):
        from .. import env
        env.init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        self._role_maker = role_maker or _RoleMaker(is_collective)
        hc = self._strategy.hybrid_configs
        self._hcg = HybridCommunicateGroup(
            dp_degree=hc.get("dp_degree", 1), mp_degree=hc.get("mp_degree", 1),
            pp_degree=hc.get("pp_degree", 1),
            sharding_degree=hc.get("sharding_degree", 1),
            sep_degree=hc.get("sep_degree", 1))
        set_hybrid_communicate_group(self._hcg)
        self._is_initialized = True
        return self

    # ------------------------------------------------------------- topology
    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        return self._hcg

    def worker_num(self):
        return self._role_maker._worker_num()

    def worker_index(self):
        return self._role_maker._worker_index()

    def is_first_worker(self):
        return self.worker_index() == 0

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    # ------------------------------------------------------------ wrapping
    def distributed_model(self, model):
        """Reference fleet_base.py:882 — wrap by parallel mode."""
        mode = self._hcg.get_parallel_mode()
        from .meta_parallel.parallel_layers.pp_layers import PipelineLayer
        if isinstance(model, PipelineLayer) or mode == "PipelineParallel":
            return PipelineParallel(model, self._hcg, self._strategy)
        if mode == "TensorParallel":
            return TensorParallel(model, self._hcg, self._strategy)
        if mode == "ShardingParallel":
            return ShardingParallel(model, self._hcg, self._strategy)
        if mode == "DataParallel":
            return DataParallel(model, self._strategy)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """Reference fleet_base.py:829 — returns a HybridParallelOptimizer
        facade; sharding/clip behavior is applied inside the SPMD step."""
        if strategy is not None:
            self._strategy = strategy
        self._user_defined_optimizer = optimizer
        from .meta_optimizers.hybrid_optimizer import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    def distributed_scaler(self, scaler):
        return scaler

    # ------------------------------------------------------- train builders
    def distributed_train_step(self, layer, loss_fn, optimizer):
        """TPU-native: build the jit hybrid step for (layer, loss, opt)."""
        from ..spmd import make_spmd_train_step
        zero = 0
        if self._strategy.sharding:
            zero = int(self._strategy.sharding_configs.get("stage", 1))
        acc = int(self._strategy.pipeline_configs.get("accumulate_steps", 1)) \
            if self._strategy.pipeline else 1
        inner = getattr(optimizer, "_inner_opt", optimizer)
        return make_spmd_train_step(layer, loss_fn, inner, self._hcg,
                                    zero_stage=zero, accumulate_steps=acc)


fleet = Fleet()

# module-level API (paddle.distributed.fleet.init style)
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
distributed_scaler = fleet.distributed_scaler
distributed_train_step = fleet.distributed_train_step
get_hybrid_communicate_group = lambda: fleet._hcg or get_hybrid_communicate_group()  # noqa: E731
worker_num = fleet.worker_num
worker_index = fleet.worker_index

