"""Distributed metric reductions (reference: fleet/metrics/metric.py —
all-reduced global metrics over the worker group).

TPU-native: the all-reduce is the eager collective (identity in a single
process, psum across the mesh inside shard_map/multi-process runs).

The reference-parity functions intentionally shadow the ``sum``/``max``/
``min`` builtins (``fleet.metrics.sum`` IS the API); internal code uses
``builtins.*``.  Scalars reduce as raw device arrays — no per-value Tensor
wrapper object — and ``all_reduce_metrics`` batches a whole dict of step
metrics into ONE collective (the telemetry cross-host aggregation path:
one all-reduce per training report instead of one per metric).
"""

from __future__ import annotations

import builtins
from typing import Dict, Mapping

import numpy as np


def _np(x):
    return np.asarray(getattr(x, "_data", x), dtype=np.float64)


def _allreduce(value, op="sum"):
    from ...collective import all_reduce, ReduceOp
    import jax.numpy as jnp
    ops = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX, "min": ReduceOp.MIN}
    out = all_reduce(jnp.asarray(value), op=ops[op])
    return np.asarray(getattr(out, "_data", out))


def all_reduce_metrics(metrics: Mapping[str, float], op: str = "sum"
                       ) -> Dict[str, float]:
    """Reduce a whole dict of scalar metrics with ONE collective: values
    pack into a single vector, reduce once, unpack by key.  Identity in a
    single process; in multi-process runs the vector rides ONE
    ``process_allgather`` (host-level — the eager device all_reduce is
    unsupported cross-process) and reduces host-side.  Used by
    ``telemetry.TrainMonitor.aggregate()`` for global throughput
    (``op="sum"``) and straggler wall time (``op="max"``)."""
    if not metrics:
        return {}
    # goodput seam: this is the host-level collective every telemetry
    # roll-up rides — its wall is ``comm`` time on the active ledger
    from ....telemetry_ledger import ledger_span
    with ledger_span("comm"):
        keys = list(metrics)
        vec = np.asarray([float(metrics[k]) for k in keys], np.float64)
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            rows = np.asarray(multihost_utils.process_allgather(vec),
                              np.float64).reshape(-1, len(keys))
            red = {"sum": rows.sum(0), "max": rows.max(0),
                   "min": rows.min(0)}[op]
            return {k: float(v) for k, v in zip(keys, red)}
        out = np.asarray(_allreduce(vec, op), np.float64).reshape(-1)
        return {k: float(v) for k, v in zip(keys, out)}


def sum(input, scope=None, util=None):
    return float(_allreduce(_np(input).sum(), "sum"))


def max(input, scope=None, util=None):
    return float(_allreduce(_np(input).max(), "max"))


def min(input, scope=None, util=None):
    return float(_allreduce(_np(input).min(), "min"))


def mean(input, scope=None, util=None):
    total = _allreduce(np.array([_np(input).sum(), _np(input).size]), "sum")
    return float(total[0] / builtins.max(total[1], 1))


def acc(correct, total, scope=None, util=None):
    agg = _allreduce(np.array([_np(correct).sum(), _np(total).sum()]), "sum")
    return float(agg[0] / builtins.max(agg[1], 1e-12))


def mae(abserr, total_ins_num, scope=None, util=None):
    agg = _allreduce(np.array([_np(abserr).sum(), float(total_ins_num)]), "sum")
    return float(agg[0] / builtins.max(agg[1], 1e-12))


def mse(sqrerr, total_ins_num, scope=None, util=None):
    agg = _allreduce(np.array([_np(sqrerr).sum(), float(total_ins_num)]), "sum")
    return float(agg[0] / builtins.max(agg[1], 1e-12))


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    return float(np.sqrt(mse(sqrerr, total_ins_num)))


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Global AUC from the threshold-bucket stats (reference metric.py:144)."""
    pos = _allreduce(_np(stat_pos), "sum")
    neg = _allreduce(_np(stat_neg), "sum")
    # walk buckets high→low accumulating the trapezoid area
    tot_pos = tot_neg = 0.0
    area = 0.0
    for b in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[b]
        new_neg = tot_neg + neg[b]
        area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0 or tot_neg == 0:
        return 0.5
    return float(area / (tot_pos * tot_neg))
