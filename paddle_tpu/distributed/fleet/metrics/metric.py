"""Distributed metric reductions (reference: fleet/metrics/metric.py —
all-reduced global metrics over the worker group).

TPU-native: the all-reduce is the eager collective (identity in a single
process, psum across the mesh inside shard_map/multi-process runs).
"""

from __future__ import annotations

import builtins

import numpy as np


def _np(x):
    return np.asarray(getattr(x, "_data", x), dtype=np.float64)


def _allreduce(value, op="sum"):
    from ...collective import all_reduce, ReduceOp
    from ....core.tensor import Tensor
    import jax.numpy as jnp
    t = Tensor(jnp.asarray(value))
    ops = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX, "min": ReduceOp.MIN}
    out = all_reduce(t, op=ops[op])
    return np.asarray(getattr(out, "_data", out))


def sum(input, scope=None, util=None):
    return float(_allreduce(_np(input).sum(), "sum"))


def max(input, scope=None, util=None):
    return float(_allreduce(_np(input).max(), "max"))


def min(input, scope=None, util=None):
    return float(_allreduce(_np(input).min(), "min"))


def mean(input, scope=None, util=None):
    total = _allreduce(np.array([_np(input).sum(), _np(input).size]), "sum")
    return float(total[0] / builtins.max(total[1], 1))


def acc(correct, total, scope=None, util=None):
    agg = _allreduce(np.array([_np(correct).sum(), _np(total).sum()]), "sum")
    return float(agg[0] / builtins.max(agg[1], 1e-12))


def mae(abserr, total_ins_num, scope=None, util=None):
    agg = _allreduce(np.array([_np(abserr).sum(), float(total_ins_num)]), "sum")
    return float(agg[0] / builtins.max(agg[1], 1e-12))


def mse(sqrerr, total_ins_num, scope=None, util=None):
    agg = _allreduce(np.array([_np(sqrerr).sum(), float(total_ins_num)]), "sum")
    return float(agg[0] / builtins.max(agg[1], 1e-12))


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    return float(np.sqrt(mse(sqrerr, total_ins_num)))


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Global AUC from the threshold-bucket stats (reference metric.py:144)."""
    pos = _allreduce(_np(stat_pos), "sum")
    neg = _allreduce(_np(stat_neg), "sum")
    # walk buckets high→low accumulating the trapezoid area
    tot_pos = tot_neg = 0.0
    area = 0.0
    for b in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[b]
        new_neg = tot_neg + neg[b]
        area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0 or tot_neg == 0:
        return 0.5
    return float(area / (tot_pos * tot_neg))
