from .metric import acc, all_reduce_metrics, auc, max, mean, min, rmse, sum  # noqa: F401

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "mse", "acc", "mean",
           "all_reduce_metrics"]

from .metric import mae, mse  # noqa: F401
