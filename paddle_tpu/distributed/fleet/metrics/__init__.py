from .metric import acc, auc, max, mean, min, rmse, sum  # noqa: F401

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "mse", "acc", "mean"]

from .metric import mae, mse  # noqa: F401
