"""DistributedStrategy.

Reference: python/paddle/distributed/fleet/base/distributed_strategy.py:105
wrapping distributed_strategy.proto:269 (nested feature configs with enable
bits: ShardingConfig:33, HybridConfig:51, AMPConfig:58, RecomputeConfig:27…).

TPU-native: one plain dataclass-style object with the same nested dict
surface; consumed by the SPMD engine instead of meta-optimizer selection.
"""

from __future__ import annotations

import copy
from typing import Any, Dict


_DEFAULTS: Dict[str, Any] = {
    "amp": False,
    "amp_configs": {"init_loss_scaling": 32768.0, "incr_every_n_steps": 1000,
                    "decr_every_n_nan_or_inf": 2, "incr_ratio": 2.0,
                    "decr_ratio": 0.5, "use_dynamic_loss_scaling": True,
                    "custom_white_list": [], "custom_black_list": [],
                    "use_pure_fp16": False, "use_bf16": True, "level": "O1"},
    "recompute": False,
    "recompute_configs": {"checkpoints": [], "enable_offload": False},
    "sharding": False,
    "sharding_configs": {"stage": 1, "sharding_degree": 1, "segment_broadcast_MB": 32,
                         "gradient_merge_acc_step": 1, "offload": False},
    "pipeline": False,
    "pipeline_configs": {"accumulate_steps": 1, "micro_batch_size": 1,
                         "schedule_mode": "1F1B"},
    "tensor_parallel": False,
    "tensor_parallel_configs": {"tensor_parallel_degree": 1, "tensor_init_seed": -1},
    "hybrid_configs": {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                       "sharding_degree": 1, "sep_degree": 1,
                       # ≙ reference pp_configs (virtual pipeline = the
                       # interleaved 1F1B schedule; spmd_pipeline_interleaved)
                       "pp_configs": {"virtual_pipeline_degree": 1}},
    "gradient_merge": False,
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    "lamb": False,
    "lamb_configs": {"lamb_weight_decay": 0.01, "exclude_from_weight_decay": []},
    "lars": False,
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005},
    "localsgd": False,
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "dgc": False,
    "dgc_configs": {"rampup_begin_step": 0},
    "gradient_scale_configs": {"scale_strategy": "avg"},
    "fuse_all_reduce_ops": True,
    "fuse_grad_size_in_MB": 32,
    "nccl_comm_num": 1,
    "find_unused_parameters": False,
    "heter_ccl_mode": False,
    "without_graph_optimization": True,
    "asp": False,
    "a_sync": False,
    "a_sync_configs": {"k_steps": -1},
    "auto": False,
    "semi_auto": False,
    "auto_search": False,
}


class DistributedStrategy:
    def __init__(self):
        self.__dict__["_cfg"] = copy.deepcopy(_DEFAULTS)

    def __getattr__(self, name):
        cfg = self.__dict__["_cfg"]
        if name in cfg:
            return cfg[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        cfg = self.__dict__["_cfg"]
        if name.endswith("_configs") and name in cfg and isinstance(value, dict):
            cfg[name].update(value)
        else:
            cfg[name] = value

    def to_dict(self):
        return copy.deepcopy(self._cfg)

    def __repr__(self):
        on = [k for k, v in self._cfg.items() if v is True]
        return f"DistributedStrategy(enabled={on})"
