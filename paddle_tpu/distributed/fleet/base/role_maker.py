"""Role makers (reference: fluid/incubate/fleet/base/role_maker.py:480
PaddleCloudRoleMaker, UserDefinedRoleMaker).

The role maker answers "who am I in this job": worker/server index, world
size, endpoints — derived from the PaddleCloud scheduler's env contract
(PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID / TRAINING_ROLE / POD_IP /
PADDLE_PORT / PADDLE_TRAINER_ENDPOINTS).  In this TPU framework there is no
parameter-server runtime (SURVEY §7 declares the PS stack a non-goal), so
PSERVER roles are recognized and reported but ``is_server`` jobs cannot
enter the collective path; everything else is a drop-in surface for code
written against fleet.init(role_maker=...).
"""

from __future__ import annotations

import os
from typing import List, Optional


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints: List[str] = []
        self._server_endpoints: List[str] = []
        self._role: Optional[int] = None
        self._current_id = -1
        self._role_is_generated = False

    def _generate_role(self):
        raise NotImplementedError

    def _ensure(self):
        if not self._role_is_generated:
            self._generate_role()

    # -- queries (reference RoleMakerBase surface) --------------------------
    def _is_worker(self) -> bool:
        self._ensure()
        return self._role == Role.WORKER

    is_worker = _is_worker

    def _is_server(self) -> bool:
        self._ensure()
        return self._role == Role.SERVER

    is_server = _is_server

    def _is_first_worker(self) -> bool:
        return self._is_worker() and self._worker_index() == 0

    is_first_worker = _is_first_worker

    def _worker_num(self) -> int:
        self._ensure()
        return max(len(self._worker_endpoints), 1)

    worker_num = _worker_num

    def _server_num(self) -> int:
        self._ensure()
        return len(self._server_endpoints)

    server_num = _server_num

    def _worker_index(self) -> int:
        self._ensure()
        return self._current_id

    worker_index = _worker_index

    def _server_index(self) -> int:
        self._ensure()
        return self._current_id

    server_index = _server_index

    def _get_trainer_endpoints(self) -> List[str]:
        self._ensure()
        return list(self._worker_endpoints)

    get_trainer_endpoints = _get_trainer_endpoints

    def _get_pserver_endpoints(self) -> List[str]:
        self._ensure()
        return list(self._server_endpoints)

    get_pserver_endpoints = _get_pserver_endpoints

    def role_id(self) -> int:
        return self._worker_index() if self._is_worker() else self._server_index()


class PaddleCloudRoleMaker(RoleMakerBase):
    """Reads the PaddleCloud env contract (reference role_maker.py:480).

    Collective mode (the TPU path): every process is a TRAINER; identity
    comes from PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
    PADDLE_TRAINER_ENDPOINTS.  PS mode parses TRAINING_ROLE and the
    server lists for surface parity.
    """

    def __init__(self, is_collective: bool = False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._kwargs = kwargs

    def _generate_role(self):
        if self._is_collective:
            self._worker_endpoints = [
                e for e in os.getenv("PADDLE_TRAINER_ENDPOINTS", "").split(",")
                if e]
            self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
            if not self._worker_endpoints:
                n = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
                self._worker_endpoints = [f"127.0.0.1:{6170 + i}"
                                          for i in range(n)]
            self._role = Role.WORKER
        else:
            role = os.getenv("TRAINING_ROLE", "TRAINER").upper()
            if role not in ("TRAINER", "PSERVER"):
                raise ValueError(
                    f"TRAINING_ROLE must be PSERVER or TRAINER, got {role!r}")
            self._server_endpoints = [
                e for e in os.getenv("PADDLE_PSERVERS_IP_PORT_LIST",
                                     "").split(",") if e]
            self._worker_endpoints = [
                e for e in os.getenv("PADDLE_TRAINER_ENDPOINTS", "").split(",")
                if e]
            if role == "TRAINER":
                self._role = Role.WORKER
                self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
                if not self._worker_endpoints:
                    # PS-mode trainers usually don't see each other's
                    # endpoints; world size still comes from the scheduler
                    n = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
                    self._worker_endpoints = [f"trainer:{i}" for i in range(n)]
            else:
                self._role = Role.SERVER
                ip = os.getenv("POD_IP", "127.0.0.1")
                port = os.getenv("PADDLE_PORT", "")
                me = f"{ip}:{port}"
                if me not in self._server_endpoints:
                    # duplicate/ambiguous identity is worse than failing fast
                    # (the reference raises on an unmatched current endpoint)
                    raise ValueError(
                        f"current server endpoint {me!r} not in "
                        f"PADDLE_PSERVERS_IP_PORT_LIST {self._server_endpoints}")
                self._current_id = self._server_endpoints.index(me)
        self._role_is_generated = True


class UserDefinedRoleMaker(RoleMakerBase):
    """Roles passed explicitly (reference UserDefinedRoleMaker)."""

    def __init__(self, current_id: int = 0, role: int = Role.WORKER,
                 worker_num: int = 1, server_endpoints: Optional[List[str]] = None,
                 worker_endpoints: Optional[List[str]] = None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._server_endpoints = list(server_endpoints or [])
        self._worker_endpoints = list(
            worker_endpoints or [f"127.0.0.1:{6170 + i}"
                                 for i in range(worker_num)])

    def _generate_role(self):
        self._role_is_generated = True
