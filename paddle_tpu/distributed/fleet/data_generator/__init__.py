from .data_generator import (DataGenerator, MultiSlotDataGenerator,  # noqa: F401
                             MultiSlotStringDataGenerator)

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]
