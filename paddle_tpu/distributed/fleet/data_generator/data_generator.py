"""Slot-format data generators (reference: fleet/data_generator/
data_generator.py — user subclasses generate_sample; run_from_stdin emits
the MultiSlot text protocol consumed by the dataset pipeline)."""

from __future__ import annotations

import sys
from typing import Iterable


class DataGenerator:
    def __init__(self):
        self._line_limit = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        """Override: return a zero-arg generator yielding
        [(slot_name, values), ...] per sample."""
        raise NotImplementedError(
            "Please rewrite this function to return a list or tuple: " +
            "[(name, [feasign, ...]), ...]")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _format(self, slots) -> str:
        raise NotImplementedError

    def run_from_stdin(self):
        for line in sys.stdin:
            gen = self.generate_sample(line)
            for slots in gen():
                sys.stdout.write(self._format(slots))

    def run_from_memory(self, lines: Iterable[str]):
        out = []
        for line in lines:
            gen = self.generate_sample(line)
            for slots in gen():
                out.append(self._format(slots))
        return out


class MultiSlotDataGenerator(DataGenerator):
    """numeric slots: `<n> v1 ... vn` per slot (reference MultiSlot text
    protocol)."""

    def _format(self, slots) -> str:
        parts = []
        for _name, values in slots:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    def _format(self, slots) -> str:
        parts = []
        for _name, values in slots:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"
