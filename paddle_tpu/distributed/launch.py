"""Process launcher: ``python -m paddle_tpu.distributed.launch``.

Reference: python/paddle/distributed/launch.py → fleet/launch.py —
``launch_collective`` (launch.py:333) builds a Cluster/Pod, spawns one
process per device with PADDLE_* env vars (launch_utils.py), watches
children and aborts/restarts on failure; elastic mode re-execs with a new
world (fleet/elastic/manager.py:130).

TPU-native: one process per *host* (not per chip — XLA owns all local chips
in a single process), ``jax.distributed`` coordination service in place of
the TCP comm-id rendezvous, and the watch loop keeps the reference's
exit-code protocol (ELASTIC_EXIT_CODE=101 → relaunch with current peers).
On a single host with N chips the launcher simply runs ONE process: device
parallelism comes from the mesh, so nproc_per_node exists only for
CPU-simulation (`--devices cpu --nproc N` sets
xla_force_host_platform_device_count).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

ELASTIC_EXIT_CODE = 101   # reference fleet/elastic: restart-me protocol
RESCALE_EXIT_CODE = 102   # restart with a recomputed world size


def _drain(procs, grace: float = 10.0):
    """Wait for SIGTERM'd children to exit; escalate to SIGKILL after the
    grace period so a relaunch never overlaps stale trainers."""
    deadline = time.time() + grace
    for p in procs.values():
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a distributed paddle_tpu training job")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count, or elastic range 'min:max'")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", ""),
                   help="coordinator host:port (first node's address)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (TPU: leave 1 — XLA owns all "
                        "local chips; >1 only for CPU simulation)")
    p.add_argument("--devices", type=str, default="",
                   help="'cpu' forces CPU simulation with "
                        "xla_force_host_platform_device_count=nproc_per_node")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="restarts allowed on ELASTIC_EXIT_CODE before giving up")
    p.add_argument("--elastic_level", type=int, default=0,
                   help="0: restart only on exit code 101/102; 1 "
                        "(fault-tolerant, ≙ reference manager.py:178): also "
                        "restart the pod when a trainer crashes abnormally")
    p.add_argument("--elastic_store", type=str,
                   default=os.environ.get("PADDLE_ELASTIC_STORE", ""),
                   help="ElasticManager store dir; enables RESCALE (102) "
                        "handling: world is recomputed from alive membership "
                        "on relaunch")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _child_env(args, local_rank: int, world: int, nproc: int) -> dict:
    env = dict(os.environ)
    rank = args.node_rank * nproc + local_rank
    env["PADDLE_TRAINER_ID"] = str(rank)
    env["PADDLE_TRAINERS_NUM"] = str(world)
    if args.master:
        env["PADDLE_MASTER"] = args.master
    env["PADDLE_LOCAL_RANK"] = str(local_rank)
    env["FLAGS_selected_tpus"] = str(local_rank)
    if args.elastic_store:
        # children see the store target without re-plumbing it themselves
        env["PADDLE_ELASTIC_STORE"] = str(args.elastic_store)
    if args.devices == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env["PADDLE_TPU_PLATFORM"] = "cpu"
        prev = env.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in prev:
            env["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count="
                                + str(max(nproc, 1))).strip()
    return env


def _rescaled_world(args, world: int, nproc: int):
    """Recompute (world, nproc) from alive elastic-store membership.

    ≙ fleet/elastic/manager.py: on RESCALE the new world is the set of hosts
    with fresh heartbeat leases.  Without a store we can only restart with
    the same world (and say so).
    """
    is_tcp = str(args.elastic_store or "").startswith("tcp://")
    if not args.elastic_store or (not is_tcp and
                                  not os.path.isdir(args.elastic_store)):
        print("[launch] RESCALE requested but no --elastic_store; "
              "relaunching with unchanged world", file=sys.stderr)
        return world, nproc
    from .fleet.elastic import read_alive_ranks
    ttl = float(os.environ.get("PADDLE_ELASTIC_TTL", "10"))
    alive = len(read_alive_ranks(args.elastic_store, ttl))
    lo, _, hi = str(args.nnodes).partition(":")
    np_min = int(lo) if lo else 1
    np_max = int(hi) if hi else np_min  # fixed --nnodes N means N is the cap
    single_node = np_max <= 1
    if args.devices == "cpu":
        # the nnodes range counts nodes; the simulated world counts processes
        np_min *= max(args.nproc_per_node, 1)
        np_max *= max(args.nproc_per_node, 1)
    new_world = max(np_min, min(alive or world, np_max))
    if args.devices == "cpu":
        if single_node:
            # children are the simulated "hosts", so nproc tracks the world
            return new_world, new_world
        print("[launch] multi-node CPU-sim rescale keeps nproc_per_node "
              "(per-node process counts cannot be re-split safely)",
              file=sys.stderr)
        return new_world, nproc
    return new_world, nproc


def _maybe_host_store(args):
    """Host the native TCP store in-process when this launcher is the store's
    home (≙ fleet/elastic/manager.py assuming an ambient etcd — here the
    framework carries its own): for ``--elastic_store tcp://host:port``, the
    node whose rank is 0 (or a loopback host) binds the port; peers dial it.
    Returns the StoreServer handle (kept alive for the launcher's lifetime)
    or None."""
    target = str(args.elastic_store or "")
    if not target.startswith("tcp://"):
        return None
    host, _, port = target[len("tcp://"):].rpartition(":")
    local = host in ("127.0.0.1", "localhost", "0.0.0.0", "")
    if not (local or args.node_rank == 0):
        return None
    from .store import StoreServer
    from ..csrc import load_library
    load_library("kv_store")  # outside the try: a missing / unbuildable
    # native library must surface as itself, not as a port error
    try:
        return StoreServer(port=int(port or 0))
    except OSError as e:
        # Bind failed.  Only "another launcher on this host already owns the
        # port" is benign — confirm by dialing it; any other failure
        # (permission, bad port) must surface, or the workers hang forever
        # dialing a store that never comes up.
        import socket as _socket
        try:
            with _socket.create_connection(
                    ("127.0.0.1", int(port or 0)), timeout=2.0):
                return None  # live listener: another launcher hosts the store
        except OSError:
            raise RuntimeError(
                f"--elastic_store {target}: could not bind the store port "
                f"and nothing is listening on it") from e


def launch(argv=None) -> int:
    args = _parse_args(argv)
    nnodes = int(str(args.nnodes).split(":")[0])
    world = nnodes * args.nproc_per_node if args.devices == "cpu" else nnodes
    nproc = args.nproc_per_node if args.devices == "cpu" else 1
    os.makedirs(args.log_dir, exist_ok=True)
    _store_server = _maybe_host_store(args)  # noqa: F841 (lifetime anchor)

    restarts = 0
    while True:
        procs = []
        for lr in range(nproc):
            log = open(os.path.join(args.log_dir, f"workerlog.{lr}"), "a")
            cmd = [sys.executable, args.training_script] + args.training_script_args
            procs.append((subprocess.Popen(
                cmd, env=_child_env(args, lr, world, nproc),
                stdout=log if lr > 0 else None,
                stderr=subprocess.STDOUT if lr > 0 else None), log))

        # watch loop (≙ launch_utils.py watch_local_trainers): abort the pod
        # if any child fails; honor the elastic restart/rescale exit codes
        exit_code, restart, rescale = 0, False, False
        crash_rc = 0  # real failure code behind a level-1 crash restart
        try:
            alive = {p.pid: p for p, _ in procs}
            while alive:
                for pid, p in list(alive.items()):
                    rc = p.poll()
                    if rc is None:
                        continue
                    del alive[pid]
                    if rc == ELASTIC_EXIT_CODE:
                        restart = True
                    elif rc == RESCALE_EXIT_CODE:
                        restart = rescale = True
                        # all peers must re-form the world: stop them cleanly
                        for q in alive.values():
                            q.send_signal(signal.SIGTERM)
                    elif rc != 0:
                        if args.elastic_level >= 1:
                            # fault-tolerant: a crashed trainer (incl. signal
                            # deaths, rc<0) restarts the pod like a 101
                            restart = True
                            crash_rc = rc
                        else:
                            exit_code = rc
                        for q in alive.values():
                            q.send_signal(signal.SIGTERM)
                        # reap the peers before relaunching: stale trainers
                        # hold the coordinator port / device claims and the
                        # log files of the next pod
                        _drain(alive)
                        alive = {}
                        break
                # tpulint: disable=unbounded-retry(child-process poll cadence, not a retry against a failing service — the outer restart loop is bounded by max_restarts and the sleep paces p.poll(), where backoff would only delay crash detection)
                time.sleep(0.5)
        finally:
            for _, log in procs:
                log.close()

        # once a restart/rescale is requested, peer crash codes don't veto it
        # (a 102-exiting trainer routinely breaks peers' live collectives)
        if restart:
            if restarts >= args.max_restarts:
                # a crash-looping job must not report success (ADVICE r1);
                # a level-1 crash loop reports the REAL failure code, not
                # "please restart me" (101 would loop outer supervisors)
                print("[launch] restart budget exhausted", file=sys.stderr)
                return crash_rc if crash_rc else ELASTIC_EXIT_CODE
            restarts += 1
            if rescale:
                world, nproc = _rescaled_world(args, world, nproc)
            print(f"[launch] elastic {'rescale' if rescale else 'restart'} "
                  f"{restarts}/{args.max_restarts} (world={world})",
                  file=sys.stderr)
            continue
        return exit_code


if __name__ == "__main__":
    sys.exit(launch())
