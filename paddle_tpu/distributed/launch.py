"""Process launcher: ``python -m paddle_tpu.distributed.launch``.

Reference: python/paddle/distributed/launch.py → fleet/launch.py —
``launch_collective`` (launch.py:333) builds a Cluster/Pod, spawns one
process per device with PADDLE_* env vars (launch_utils.py), watches
children and aborts/restarts on failure; elastic mode re-execs with a new
world (fleet/elastic/manager.py:130).

TPU-native: one process per *host* (not per chip — XLA owns all local chips
in a single process), ``jax.distributed`` coordination service in place of
the TCP comm-id rendezvous, and the watch loop keeps the reference's
exit-code protocol (ELASTIC_EXIT_CODE=101 → relaunch with current peers).
On a single host with N chips the launcher simply runs ONE process: device
parallelism comes from the mesh, so nproc_per_node exists only for
CPU-simulation (`--devices cpu --nproc N` sets
xla_force_host_platform_device_count).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

ELASTIC_EXIT_CODE = 101  # reference fleet/elastic: restart-me protocol


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a distributed paddle_tpu training job")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count, or elastic range 'min:max'")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", ""),
                   help="coordinator host:port (first node's address)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (TPU: leave 1 — XLA owns all "
                        "local chips; >1 only for CPU simulation)")
    p.add_argument("--devices", type=str, default="",
                   help="'cpu' forces CPU simulation with "
                        "xla_force_host_platform_device_count=nproc_per_node")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="restarts allowed on ELASTIC_EXIT_CODE before giving up")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _child_env(args, local_rank: int, world: int) -> dict:
    env = dict(os.environ)
    rank = args.node_rank * args.nproc_per_node + local_rank
    env["PADDLE_TRAINER_ID"] = str(rank)
    env["PADDLE_TRAINERS_NUM"] = str(world)
    if args.master:
        env["PADDLE_MASTER"] = args.master
    env["PADDLE_LOCAL_RANK"] = str(local_rank)
    env["FLAGS_selected_tpus"] = str(local_rank)
    if args.devices == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env["PADDLE_TPU_PLATFORM"] = "cpu"
        prev = env.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in prev:
            env["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count="
                                + str(max(args.nproc_per_node, 1))).strip()
    return env


def launch(argv=None) -> int:
    args = _parse_args(argv)
    nnodes = int(str(args.nnodes).split(":")[0])
    world = nnodes * args.nproc_per_node if args.devices == "cpu" else nnodes
    nproc = args.nproc_per_node if args.devices == "cpu" else 1
    os.makedirs(args.log_dir, exist_ok=True)

    restarts = 0
    while True:
        procs = []
        for lr in range(nproc):
            log = open(os.path.join(args.log_dir, f"workerlog.{lr}"), "a")
            cmd = [sys.executable, args.training_script] + args.training_script_args
            procs.append((subprocess.Popen(
                cmd, env=_child_env(args, lr, world),
                stdout=log if lr > 0 else None,
                stderr=subprocess.STDOUT if lr > 0 else None), log))

        # watch loop (≙ launch_utils.py watch_local_trainers): abort the pod
        # if any child fails; honor the elastic restart exit code
        exit_code, restart = 0, False
        try:
            alive = {p.pid: p for p, _ in procs}
            while alive:
                for pid, p in list(alive.items()):
                    rc = p.poll()
                    if rc is None:
                        continue
                    del alive[pid]
                    if rc == ELASTIC_EXIT_CODE:
                        restart = True
                    elif rc != 0:
                        exit_code = rc
                        for q in alive.values():
                            q.send_signal(signal.SIGTERM)
                        alive = {}
                        break
                time.sleep(0.5)
        finally:
            for _, log in procs:
                log.close()

        if restart and restarts < args.max_restarts and exit_code == 0:
            restarts += 1
            print(f"[launch] elastic restart {restarts}/{args.max_restarts}",
                  file=sys.stderr)
            continue
        return exit_code


if __name__ == "__main__":
    sys.exit(launch())
