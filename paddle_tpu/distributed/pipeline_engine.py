"""Pipeline-parallel execution engines.

Reference: the 1F1B machinery — SectionWorker (device_worker.h:538,
section_worker.cc:62-137), PipelineParallel.forward_backward_pipeline
(pipeline_parallel.py:80), p2p_communication.py SendRecvMeta handshake.

TPU-native replacements (two tiers):

1. **Stacked-stage engine** (`make_stacked_pipeline_step`) — the performant
   path.  Requires the model's repeated blocks to be parameterized as ONE
   stacked pytree with a leading layer dim (models/gpt.py does this).  The
   leading dim is split over the "pipe" mesh axis inside a partial-auto
   ``shard_map``; micro-batches flow stage-to-stage via ``ppermute``
   (spmd.spmd_pipeline).  The P2P SendRecvMeta handshake disappears — shapes
   are static; c_sync/stream ordering disappears — XLA schedules the
   collectives.  Backward through the loop gives the GPipe schedule;
   activation memory is bounded via ``jax.checkpoint`` on the stage body.

2. **Generic PipelineLayer fallback** (`make_pipeline_train_step`) — accepts
   any reference-style PipelineLayer (heterogeneous stages).  Executes the
   stages serially inside one GSPMD step with each stage's parameters placed
   on its pipe coordinate (correct placement + collectives, conservative
   overlap).  Kept so the reference API is fully usable while models migrate
   to stacked form.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from .sharding_rules import (make_spec, override_leading_axis,
                             replicated_spec)
from .spmd import shard_map as _shard_map

from ..core import rng
from .spmd import (build_param_specs, build_state_shardings, spmd_pipeline,
                   spmd_pipeline_interleaved)


def interleave_layers(x, n_stages: int, n_chunks: int):
    """Permute a [L, ...] layer stack into chunk-interleaved storage order:
    position d*(V*lpc) + v*lpc + i holds original layer (v*S + d)*lpc + i.
    A 'pipe'-sharded dim0 then gives device d exactly its V schedule chunks
    contiguously — the interleaved pipeline needs no per-step re-layout
    collective.  Inverse: ``deinterleave_layers``."""
    S, V = n_stages, n_chunks
    L = x.shape[0]
    lpc = L // (S * V)
    perm = np.array([(v * S + d) * lpc + i
                     for d in range(S) for v in range(V) for i in range(lpc)])
    return x[perm]


def deinterleave_layers(x, n_stages: int, n_chunks: int):
    """Inverse of interleave_layers (use when exporting a checkpoint trained
    with virtual_pp_degree > 1 to the plain layer order)."""
    S, V = n_stages, n_chunks
    L = x.shape[0]
    lpc = L // (S * V)
    perm = np.array([(v * S + d) * lpc + i
                     for d in range(S) for v in range(V) for i in range(lpc)])
    inv = np.empty_like(perm)
    inv[perm] = np.arange(L)
    return x[inv]


def make_pipeline_train_step(pipeline_layer, loss_fn, optimizer, hcg,
                             accumulate_steps: int = 1, monitor=None):
    """Generic fallback: GSPMD step over the hybrid mesh with stage-placed
    parameters (see module docstring, tier 2)."""
    from .spmd import make_spmd_train_step
    return make_spmd_train_step(pipeline_layer, loss_fn, optimizer, hcg,
                                accumulate_steps=accumulate_steps,
                                monitor=monitor)[:2]


def make_stacked_pipeline_step(embed_fn: Callable, block_fn: Callable,
                               head_loss_fn: Callable, params0, optimizer, hcg,
                               n_layers: int, n_microbatches: int,
                               stacked_keys, layer=None, donate: bool = True,
                               remat: bool = True, virtual_pp_degree: int = 1,
                               monitor=None):
    """Build the stacked-stage pipelined train step (tier 1).

    - embed_fn(params, x, key)        -> h            (replicated compute)
    - block_fn(block_slice, h, key)   -> h            (ONE transformer block)
    - head_loss_fn(params, h, labels) -> scalar loss  (replicated compute)
    - ``stacked_keys``: param names whose leading dim is n_layers (split
      over "pipe").
    - ``virtual_pp_degree`` V > 1 selects the interleaved schedule (≙ the
      reference's pp_configs virtual_pipeline_degree): the layer stack is
      split into S*V chunks, device d holds chunks {v*S+d}, and the
      fill/drain bubble shrinks by V (spmd.spmd_pipeline_interleaved).

    Returns (step, state0) with step(state, key, lr, x, labels) -> (state, loss).
    """
    mesh = hcg.mesh
    S = mesh.shape.get("pipe", 1)
    V = max(int(virtual_pp_degree), 1) if S > 1 else 1  # serial path ignores V
    assert n_layers % max(S * V, 1) == 0, \
        "n_layers must divide pp degree * virtual_pp_degree"
    M = n_microbatches
    if V > 1 and M % S:
        raise ValueError(f"n_microbatches ({M}) must be a multiple of the "
                         f"pp degree ({S}) when virtual_pp_degree > 1")
    if V > 1:
        # store stacked params chunk-interleaved from init: the contiguous
        # 'pipe' shard of each device IS its V schedule chunks, so the hot
        # path has no re-layout collective.  TrainState (and checkpoints of
        # it) hold this order; deinterleave_layers() converts back.
        params0 = dict(params0)
        for k in stacked_keys:
            params0[k] = interleave_layers(params0[k], S, V)

    # mark stacked params so build_param_specs shards dim0 over pipe
    if layer is not None:
        for name, p in layer.named_parameters():
            if name in stacked_keys:
                p._pipe_stacked = True

    opt_state0 = optimizer.init_state(params0)
    state0 = {"params": params0, "opt": opt_state0, "buffers": {}}
    p_specs = build_param_specs(params0, mesh, layer, 0)
    if S > 1:
        for k in stacked_keys:
            p_specs[k] = override_leading_axis(
                p_specs[k], len(params0[k].shape), "pipe")
    state_sh = build_state_shardings(state0, p_specs, mesh, 0, params0)

    in_specs_pipe = {k: (make_spec("pipe") if k in stacked_keys
                         else replicated_spec()) for k in params0}

    def loss_of(params, key, x, labels):
        h = embed_fn(params, x, key)
        # micro-batch the sequence of activations
        mb = h.reshape((M, h.shape[0] // M) + h.shape[1:])

        def run_blocks(hmb, blocks):
            """Scan a stack of transformer blocks over the activations."""
            def body(carry, sl):
                fn = jax.checkpoint(block_fn) if remat else block_fn
                return fn(sl, carry, key), None
            out, _ = jax.lax.scan(body, hmb, blocks)
            return out

        if S > 1 and V > 1:
            # params are stored chunk-interleaved (see init above): the local
            # 'pipe' shard [V*lpc, ...] reshapes to this device's V chunks
            # with zero collective traffic
            lpc = n_layers // (S * V)
            block_params = {k: params[k] for k in stacked_keys}

            def chunk_fn(chunk_blocks, hmb, mb_idx, v):
                return run_blocks(hmb, chunk_blocks)

            def pipelined(blocks, mbs):
                local = jax.tree_util.tree_map(
                    lambda a: a.reshape((V, lpc) + a.shape[1:]), blocks)
                return spmd_pipeline_interleaved(chunk_fn, local, mbs, S, V,
                                                 axis="pipe")

            out_mb = _shard_map(
                pipelined, mesh=mesh,
                in_specs=({k: make_spec("pipe") for k in stacked_keys},
                          replicated_spec()),
                out_specs=replicated_spec(),
                axis_names={"pipe"})(block_params, mb)
        elif S > 1:
            block_params = {k: params[k] for k in stacked_keys}

            def stage_fn(local_blocks, hmb, mb_idx):
                return run_blocks(hmb, local_blocks)

            def pipelined(blocks, mbs):
                return spmd_pipeline(stage_fn, blocks, mbs, S, axis="pipe")

            # check_vma left ON: spmd_pipeline marks its carry varying via
            # pvary, so the varying-manual-axes checker passes and catches
            # real replication bugs
            out_mb = _shard_map(
                pipelined, mesh=mesh,
                in_specs=({k: make_spec("pipe") for k in stacked_keys},
                          replicated_spec()),
                out_specs=replicated_spec(),
                axis_names={"pipe"})(block_params, mb)
        else:
            out_mb = run_blocks(mb.reshape((-1,) + mb.shape[2:]),
                                {k: params[k] for k in stacked_keys})
            out_mb = out_mb.reshape(mb.shape[:2] + out_mb.shape[1:])

        h_out = out_mb.reshape((-1,) + out_mb.shape[2:])
        return head_loss_fn(params, h_out, labels)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state, key, lr, x, labels):
        loss, grads = jax.value_and_grad(loss_of)(state["params"], key, x, labels)
        new_params, new_opt = optimizer.update(grads, state["opt"], state["params"],
                                               lr=lr)
        new_params = jax.lax.with_sharding_constraint(
            new_params, {k: NamedSharding(mesh, p_specs[k]) for k in new_params})
        return {"params": new_params, "opt": new_opt, "buffers": {}}, loss

    def place(state):
        return jax.tree_util.tree_map(lambda x, s: jax.device_put(x, s), state,
                                      state_sh, is_leaf=lambda x: hasattr(x, "shape"))

    from ..telemetry import instrument_train_step
    return instrument_train_step(step, monitor, "pipeline"), place(state0)
