"""Quantized gradient collectives — the pluggable grad-comm policy layer.

Every data-parallel trainer in the framework synchronizes gradients (or,
for LocalSGD, parameters) across replicas.  At scale the bytes those
collectives put on the wire are the bottleneck, and full-precision fp32
traffic is 2-4x larger than it needs to be.  This module factors the
choice of wire format out of the trainers into a POLICY:

``fp32``     today's behavior and the default: full-precision
             ``lax.pmean``/``psum_scatter``.  Zero risk, zero savings.
``bf16``     cast -> reduce -> cast back: a 2x traffic cut whose error
             (bf16 has an fp32 exponent) is usually invisible next to the
             gradient noise floor.
``int8_ef``  EQuARX-style block-quantized reduction
             (https://arxiv.org/pdf/2506.17615): per-block fp32 scales +
             an int8 payload, composed inside ``shard_map`` as

                 quantize -> all_to_all (int8)        # shard exchange
                 -> dequantize-accumulate in fp32     # local reduce
                 -> requantize -> all_gather (int8)   # result broadcast
                 -> dequantize

             so EVERY hop on the wire is int8 (+ 4 bytes per ``block``
             elements of scale) — a ~3.9x byte cut at the default
             ``block=256``.  An error-feedback residual (Karimireddy et
             al. 2019; the same machinery ``dgc.py`` uses for top-k
             sparsification) carries each replica's quantization error
             into the next step, which preserves convergence: the
             residual update helpers here (``ef_accumulate`` /
             ``ef_residual``) are shared with DGC so the two
             compressed-exchange paths cannot drift.

Two application modes, honestly separated:

- **wire mode** (``all_reduce``/``reduce_scatter`` with a bound mesh
  ``axis``, i.e. inside ``shard_map``): the composition above really runs
  and the collectives really move quantized bytes.  LocalSGD's parameter
  averaging and the module-level ``compressed_all_reduce`` /
  ``compressed_reduce_scatter`` use this mode.
- **local mode** (``apply_local``, no axis): the same quantize ->
  (identity reduce) -> requantize -> dequantize pipeline with R=1, bit
  -identical to the wire composition on one replica.  The GSPMD trainers
  (``zero.py``, ``spmd.py`` steps, ``jit/functional.py``) use this mode:
  there XLA owns the collective schedule (the dp reduction is inserted
  inside ``value_and_grad``), so the policy governs the NUMERICS of the
  exchanged gradient and the byte accounting, while true quantized hops
  need the shard_map composition.  This keeps a laptop run's loss curve
  faithful to what the policy does on a pod.

Byte accounting (``wire_bytes``) uses the logical ring-all-reduce model
in the large-R limit: a reduction of N elements moves ~2 payload passes
per replica (reduce-scatter + all-gather halves), so

    fp32:    2 * 4N
    bf16:    2 * 2N
    int8_ef: 2 * (N + 4 * ceil(N / block))

independent of the axis size — well-defined on any mesh, including the
single-device CPU fallback.  ``telemetry.TrainMonitor.record_comm``
turns these into per-step ``comm`` events (see docs/DISTRIBUTED_COMM.md).

Quantization error bound (the documented contract, pinned by
tests/test_grad_comm.py): symmetric per-block int8 with scale
``max|block| / 127`` has per-element dequantization error at most
``scale / 2 = max|block| / 254``; the two-stage all-reduce composition
(quantize contributions, requantize the mean) therefore lands within
``max|block| / 127`` of the exact fp32 mean, per block.  Constant blocks
round-trip to ~1 ulp (the max element quantizes to exactly +-127).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "DEFAULT_BLOCK", "GradCommPolicy", "Fp32Policy", "Bf16Policy",
    "Int8EfPolicy", "POLICIES", "resolve_policy",
    "compressed_all_reduce", "compressed_reduce_scatter", "tree_from_shards",
    "quantize_blocks", "dequantize_blocks", "ef_accumulate", "ef_residual",
    "wire_bytes", "comm_info", "apply_policy_local",
]

#: default quantization block (elements per fp32 scale); 256 amortizes the
#: scale overhead to ~1.6% while keeping blocks small enough that one
#: outlier only poisons 255 neighbors
DEFAULT_BLOCK = 256

_QMAX = 127.0  # symmetric int8: levels in [-127, 127] (no -128 asymmetry)


# --------------------------------------------------------------------------
# error-feedback primitives — SHARED with dgc.py (one implementation, so
# the int8 and top-k compressed exchanges cannot drift)
# --------------------------------------------------------------------------

def ef_accumulate(residual, update):
    """``v = residual + update``: fold the carried compression error into
    this step's value before compressing.  ``residual=None`` (stateless
    caller / first step) passes ``update`` through."""
    if residual is None:
        return update
    return residual + update


def ef_residual(v, sent):
    """``e' = v - sent``: what was accumulated minus what actually went on
    the wire (the DECOMPRESSED payload, so the residual carries exactly
    the error the receivers saw)."""
    return v - sent


# --------------------------------------------------------------------------
# block quantization kernels
# --------------------------------------------------------------------------

def quantize_blocks(x, block: int = DEFAULT_BLOCK):
    """Symmetric per-block int8 quantization over the LAST dimension.

    ``x``: float array whose last dim is a multiple of ``block``.  Returns
    ``(q, scales)``: ``q`` int8 with x's shape, ``scales`` fp32 shaped
    ``x.shape[:-1] + (last // block,)`` with ``scale = max|block| / 127``
    (all-zero blocks get scale 1.0 so they stay exactly zero).
    """
    shape = x.shape
    if shape[-1] % block:
        raise ValueError(f"last dim {shape[-1]} not a multiple of {block}")
    xb = x.reshape(shape[:-1] + (shape[-1] // block, block)).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xb), axis=-1)
    scales = jnp.where(amax > 0, amax / _QMAX, 1.0)
    q = jnp.clip(jnp.round(xb / scales[..., None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8).reshape(shape), scales


def dequantize_blocks(q, scales, block: int = DEFAULT_BLOCK):
    """Inverse of :func:`quantize_blocks`; returns fp32 with ``q``'s shape."""
    shape = q.shape
    qb = q.reshape(shape[:-1] + (shape[-1] // block, block)).astype(jnp.float32)
    return (qb * scales[..., None]).reshape(shape)


# --------------------------------------------------------------------------
# pytree <-> padded flat vector (one fused buffer so ONE set of collectives
# serves the whole gradient tree — the seam topology-aware bucketing will
# later split)
# --------------------------------------------------------------------------

class TreeMeta(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    n: int
    n_pad: int


def _tree_size(tree) -> int:
    return int(sum(int(np.prod(np.shape(l)))
                   for l in jax.tree_util.tree_leaves(tree)))


def _flatten_tree(tree, multiple: int, total: Optional[int] = None):
    """Concatenate all leaves (as fp32) into one flat vector zero-padded to
    ``total`` elements (or the next multiple of ``multiple``)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        raise ValueError("grad_comm: empty pytree")
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    parts = [jnp.ravel(l).astype(jnp.float32) for l in leaves]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    n = flat.shape[0]
    n_pad = total if total is not None else -(-n // multiple) * multiple
    if n_pad < n or n_pad % multiple:
        raise ValueError(
            f"grad_comm: residual/pad length {n_pad} incompatible with tree "
            f"size {n} and multiple {multiple} — was the residual built for "
            f"a different tree or axis size?")
    if n_pad > n:
        flat = jnp.concatenate([flat, jnp.zeros((n_pad - n,), jnp.float32)])
    return flat, TreeMeta(treedef, shapes, dtypes, n, n_pad)


def _unflatten_tree(flat, meta: TreeMeta):
    out, off = [], 0
    for shape, dt in zip(meta.shapes, meta.dtypes):
        size = int(np.prod(shape)) if shape else 1
        out.append(flat[off:off + size].reshape(shape).astype(dt))
        off += size
    return jax.tree_util.tree_unflatten(meta.treedef, out)


def _axis_size(axis) -> int:
    # psum of a unit constant folds to the static axis size inside shard_map
    return int(lax.psum(1, axis)) if axis is not None else 1


# --------------------------------------------------------------------------
# policies
# --------------------------------------------------------------------------

class GradCommPolicy:
    """Base policy: fp32 passthrough (today's behavior).

    The contract every policy implements:

    - ``all_reduce(tree, axis, residual)`` -> ``(mean_tree, residual')``:
      cross-replica MEAN over mesh axis ``axis`` (must be bound, i.e.
      inside shard_map) — the operation every dp trainer wants.
    - ``reduce_scatter(tree, axis, residual)`` -> ``(shard, meta,
      residual')``: each replica gets its ``1/R`` contiguous shard of the
      flattened mean (fp32); ``tree_from_shards`` reassembles.
    - ``apply_local(tree, residual)`` -> ``(tree', residual')``: the R=1
      wire composition (bit-identical numerics, no collectives) for
      GSPMD/single-process trainers.
    - ``residual_for(tree, axis_size)``: zeros of the flat padded residual
      this policy threads through state (None for stateless policies).
    - ``wire_bytes(tree)`` -> ``(pre, post)``: fp32-baseline vs this
      policy's logical ring-all-reduce bytes per step.
    """

    name = "fp32"
    #: True when the policy carries an error-feedback residual in state
    stateful = False

    # -- wire mode ---------------------------------------------------------
    def all_reduce(self, tree, axis, residual=None):
        return jax.tree_util.tree_map(
            lambda t: lax.pmean(t, axis), tree), residual

    def reduce_scatter(self, tree, axis, residual=None):
        R = _axis_size(axis)
        flat, meta = _flatten_tree(tree, max(R, 1))
        shard = lax.psum_scatter(flat, axis, scatter_dimension=0,
                                 tiled=True) / R
        return shard, meta, residual

    # -- local mode --------------------------------------------------------
    def apply_local(self, tree, residual=None):
        return tree, residual

    # -- state / accounting ------------------------------------------------
    def residual_for(self, tree, axis_size: int = 1):
        return None

    def wire_bytes(self, tree) -> Tuple[int, int]:
        n = _tree_size(tree)
        return 8 * n, 8 * n


class Bf16Policy(GradCommPolicy):
    """Cast -> reduce -> cast back: every hop moves bf16 (2x cut).  The
    reduction accumulates in bf16 — acceptable for gradient averaging
    (bf16 keeps the fp32 exponent), documented rather than hidden."""

    name = "bf16"

    def all_reduce(self, tree, axis, residual=None):
        return jax.tree_util.tree_map(
            lambda t: lax.pmean(t.astype(jnp.bfloat16), axis).astype(t.dtype),
            tree), residual

    def reduce_scatter(self, tree, axis, residual=None):
        R = _axis_size(axis)
        flat, meta = _flatten_tree(tree, max(R, 1))
        shard = lax.psum_scatter(flat.astype(jnp.bfloat16), axis,
                                 scatter_dimension=0, tiled=True)
        return shard.astype(jnp.float32) / R, meta, residual

    def apply_local(self, tree, residual=None):
        return jax.tree_util.tree_map(
            lambda t: t.astype(jnp.bfloat16).astype(t.dtype), tree), residual

    def wire_bytes(self, tree):
        n = _tree_size(tree)
        return 8 * n, 4 * n


class Int8EfPolicy(GradCommPolicy):
    """EQuARX-style block-quantized all-reduce with error feedback (see
    module docstring for the composition and the error bound)."""

    name = "int8_ef"
    stateful = True

    def __init__(self, block: int = DEFAULT_BLOCK):
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        self.block = int(block)

    # residual length: the padded flat size for this (tree, axis_size) —
    # every entry point below pads to the SAME formula so a residual built
    # once stays shape-stable across steps
    def _padded(self, n: int, R: int) -> int:
        m = self.block * max(R, 1)
        return -(-n // m) * m

    def residual_for(self, tree, axis_size: int = 1):
        return jnp.zeros((self._padded(_tree_size(tree), axis_size),),
                         jnp.float32)

    def _exchange(self, v, R: int, axis):
        """The quantized exchange on a padded flat vector ``v``: returns
        ``(mean_hat, mean_shard, sent)`` where ``sent`` is the dequantized
        OWN contribution (what the receivers saw — the EF reference) and
        ``mean_shard`` the local fp32 reduced shard (pre-requantization)."""
        shard = v.shape[0] // R
        v2 = v.reshape(R, shard)
        q1, s1 = quantize_blocks(v2, self.block)
        if R > 1:
            # hop 1 (int8): row r of q1 is this replica's contribution to
            # replica r's shard; all_to_all lands all contributions to OUR
            # shard here
            qx = lax.all_to_all(q1, axis, split_axis=0, concat_axis=0)
            sx = lax.all_to_all(s1, axis, split_axis=0, concat_axis=0)
        else:
            qx, sx = q1, s1
        # local reduce in fp32 — the accumulator never rides the wire
        mean_shard = dequantize_blocks(qx, sx, self.block).sum(0) / R
        q2, s2 = quantize_blocks(mean_shard, self.block)
        if R > 1:
            # hop 2 (int8): broadcast the requantized mean shards
            qg = lax.all_gather(q2, axis)
            sg = lax.all_gather(s2, axis)
        else:
            qg, sg = q2[None], s2[None]
        mean_hat = dequantize_blocks(qg, sg, self.block).reshape(-1)
        sent = dequantize_blocks(q1, s1, self.block).reshape(-1)
        return mean_hat, mean_shard, sent

    def _run(self, tree, axis, residual):
        R = _axis_size(axis)
        flat, meta = _flatten_tree(
            tree, self.block * R,
            total=residual.shape[0] if residual is not None else None)
        v = ef_accumulate(residual, flat)
        mean_hat, mean_shard, sent = self._exchange(v, R, axis)
        return meta, mean_hat, mean_shard, ef_residual(v, sent)

    def all_reduce(self, tree, axis, residual=None):
        meta, mean_hat, _, new_e = self._run(tree, axis, residual)
        return _unflatten_tree(mean_hat, meta), new_e

    def reduce_scatter(self, tree, axis, residual=None):
        # stops at the local fp32 shard: the only wire hop is the int8
        # all_to_all — the ZeRO-2 seam (arXiv:2004.13336) where each
        # replica updates only its own parameter shard
        meta, _, mean_shard, new_e = self._run(tree, axis, residual)
        return mean_shard, meta, new_e

    def apply_local(self, tree, residual=None):
        meta, mean_hat, _, new_e = self._run(tree, None, residual)
        return _unflatten_tree(mean_hat, meta), new_e

    def wire_bytes(self, tree):
        n = _tree_size(tree)
        scales = -(-n // self.block)
        return 8 * n, 2 * (n + 4 * scales)


POLICIES: Dict[str, Any] = {
    "fp32": GradCommPolicy,
    "bf16": Bf16Policy,
    "int8_ef": Int8EfPolicy,
}

Fp32Policy = GradCommPolicy


def resolve_policy(policy) -> GradCommPolicy:
    """``None`` / a policy name / a policy instance -> policy instance."""
    if policy is None:
        return GradCommPolicy()
    if isinstance(policy, GradCommPolicy):
        return policy
    if isinstance(policy, str):
        cls = POLICIES.get(policy)
        if cls is None:
            raise ValueError(
                f"unknown grad_comm policy {policy!r}; choose from "
                f"{sorted(POLICIES)} or pass a GradCommPolicy instance")
        return cls()
    raise TypeError(f"grad_comm must be None, a name, or a GradCommPolicy; "
                    f"got {type(policy).__name__}")


# --------------------------------------------------------------------------
# module-level API (the spelling the trainers and tests use)
# --------------------------------------------------------------------------

def compressed_all_reduce(tree, axis, policy="fp32", residual=None):
    """Cross-replica MEAN of ``tree`` over mesh axis ``axis`` under
    ``policy`` (must run inside shard_map with ``axis`` bound).  Returns
    ``(mean_tree, new_residual)``; stateless policies pass ``residual``
    through unchanged."""
    return resolve_policy(policy).all_reduce(tree, axis, residual)


def compressed_reduce_scatter(tree, axis, policy="fp32", residual=None):
    """Reduce-scatter of the flattened ``tree`` mean: each replica returns
    its contiguous fp32 shard plus the :class:`TreeMeta` needed to
    reassemble (``tree_from_shards``).  Returns ``(shard, meta,
    new_residual)``."""
    return resolve_policy(policy).reduce_scatter(tree, axis, residual)


def tree_from_shards(shard, meta: TreeMeta, axis):
    """Gather reduce-scatter shards back into the full tree (fp32 hop —
    for parity tests and consumers that need the whole tree; ZeRO-style
    consumers keep the shard)."""
    flat = lax.all_gather(shard, axis, tiled=True)
    return _unflatten_tree(flat, meta)


def wire_bytes(tree, policy="fp32") -> Dict[str, int]:
    """Host-side logical bytes-on-wire estimate for one reduction of
    ``tree`` (see module docstring for the model): ``{"pre_bytes":
    fp32-baseline, "post_bytes": policy, "elements": N}``."""
    p = resolve_policy(policy)
    pre, post = p.wire_bytes(tree)
    return {"pre_bytes": int(pre), "post_bytes": int(post),
            "elements": _tree_size(tree)}


def apply_policy_local(policy, grads, state, found_inf=None):
    """The GSPMD trainers' shared local-mode seam: apply ``policy`` to the
    grad tree, threading the error-feedback residual through the state
    dict.  Returns ``(grads', comm_state)`` where ``comm_state`` is ``{}``
    or ``{"comm_e": residual'}`` to merge into the new state; when
    ``found_inf`` is given, a skipped (non-finite) step keeps the old
    residual so garbage never folds into the error feedback."""
    if policy.name == "fp32":
        return grads, {}
    grads, new_e = policy.apply_local(grads, state.get("comm_e"))
    if not policy.stateful:
        return grads, {}
    if found_inf is not None:
        new_e = jnp.where(found_inf, state["comm_e"], new_e)
    return grads, {"comm_e": new_e}


def comm_info(tree, policy) -> Optional[Dict[str, Any]]:
    """The ``comm=`` dict ``telemetry.instrument_train_step`` feeds to
    ``TrainMonitor.record_comm`` each step — None for the fp32 default so
    default runs emit no new events (zero-diff contract)."""
    p = resolve_policy(policy)
    if p.name == "fp32":
        return None
    wb = wire_bytes(tree, p)
    return {"policy": p.name, "pre_bytes": wb["pre_bytes"],
            "post_bytes": wb["post_bytes"]}
