"""LocalSGD (reference: fleet/meta_optimizers/localsgd_optimizer.py:26).

The reference rewrites the program so each data-parallel worker trains on
its own gradient for ``k_steps`` and then block-averages the parameters
(c_allreduce on params, not grads).  TPU-native formulation: parameters and
optimizer slots carry a leading replica dimension sharded over the data
axis; the whole schedule — local grad, local update, every-k parameter
average — runs inside ONE ``shard_map``-wrapped jitted step, with the sync
point expressed as a ``lax.cond`` on the step counter so there is no host
control flow and the collective is genuinely skipped at runtime on
non-sync steps (the entire point of LocalSGD: ICI traffic drops by ~k×).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding

from .sharding_rules import make_spec, replica_stacked_spec, replicated_spec
from .spmd import shard_map as _shard_map

__all__ = ["make_localsgd_train_step"]


def make_localsgd_train_step(loss_of: Callable, params0: Dict[str, Any],
                             optimizer, mesh: Mesh, k_steps: int = 4,
                             axis: str = "data", donate: bool = True,
                             monitor=None, grad_comm=None):
    """Build a LocalSGD step over the ``axis`` mesh axis.

    ``loss_of(params, *batch) -> scalar``; ``batch`` leading dim is the
    global batch, split evenly over ``axis``.  Returns ``(step, state0)``
    with ``step(state, lr, *batch) -> (state, loss)`` where loss is the
    cross-replica mean of the local losses.  Parameters are kept per-replica
    (leading dim R, sharded on ``axis``) and block-averaged every
    ``k_steps``-th call; reading them out: ``state["params"]`` rows are
    identical right after a sync step.

    ``grad_comm``: communication policy for the every-k parameter average
    (``"fp32"`` default / ``"bf16"`` / ``"int8_ef"`` / a
    ``grad_comm.GradCommPolicy``).  The whole schedule runs inside
    shard_map, so non-fp32 policies here are WIRE-real: the sync step's
    average moves bf16 or int8(+scales) on every hop.  Stateful policies
    carry a per-replica flat ``"comm_e"`` residual (leading dim R on
    ``axis``, like DGC's accumulators) absorbing each replica's own
    quantization error into the next sync.
    """
    from .grad_comm import comm_info, resolve_policy
    policy = resolve_policy(grad_comm)
    R = mesh.shape[axis]
    if k_steps < 1:
        raise ValueError(f"k_steps must be >= 1, got {k_steps}")

    stack = lambda p: jnp.broadcast_to(p[None], (R,) + p.shape)
    params_r = jax.tree_util.tree_map(stack, params0)
    opt_r = jax.tree_util.tree_map(stack, optimizer.init_state(params0))
    state0 = {"params": params_r, "opt": opt_r,
              "count": jnp.zeros([], jnp.int32)}

    stacked = lambda leaf: replica_stacked_spec(leaf, axis)
    state_specs = {
        "params": jax.tree_util.tree_map(stacked, params_r),
        "opt": jax.tree_util.tree_map(stacked, opt_r),
        "count": replicated_spec(),
    }
    if policy.stateful:
        e0 = policy.residual_for(params0, axis_size=R)
        state0["comm_e"] = jnp.zeros((R,) + e0.shape, e0.dtype)
        state_specs["comm_e"] = make_spec(axis, None)
    state0 = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        state0, state_specs)

    def body(state, lr, *batch):
        # inside shard_map: params/opt leaves have leading dim 1 (this
        # replica); batch rows are this replica's slice
        params = jax.tree_util.tree_map(lambda a: a[0], state["params"])
        opt = jax.tree_util.tree_map(lambda a: a[0], state["opt"])
        count = state["count"] + 1

        loss, grads = jax.value_and_grad(loss_of)(params, *batch)
        new_params, new_opt = optimizer.update(grads, opt, params, lr=lr)

        # lax.cond, NOT jnp.where: where would execute the pmean every step
        # and merely discard it — the collective must be skipped at runtime
        # on non-sync steps or LocalSGD saves no ICI traffic at all
        sync = (count % k_steps) == 0

        from .spmd import ensure_varying

        def _revary(p):
            # pmean output is replicated; the skip branch stays varying —
            # re-mark so both lax.cond branches type-check under the VMA
            # checker (the values ARE equal across replicas post-pmean)
            return ensure_varying(p, axis)

        e = state["comm_e"][0] if policy.stateful else None
        if policy.name == "fp32":
            new_params = lax.cond(
                sync,
                lambda ps: jax.tree_util.tree_map(
                    lambda p: _revary(lax.pmean(p, axis)), ps),
                lambda ps: ps,
                new_params)
            new_e = e
        else:
            def sync_branch(args):
                ps, e_ = args
                avg, e2 = policy.all_reduce(ps, axis, e_)
                avg = jax.tree_util.tree_map(_revary, avg)
                return avg, (e2 if e2 is None else _revary(e2))

            new_params, new_e = lax.cond(
                sync, sync_branch, lambda args: args, (new_params, e))

        out = {"params": jax.tree_util.tree_map(lambda a: a[None], new_params),
               "opt": jax.tree_util.tree_map(lambda a: a[None], new_opt),
               "count": count}
        if policy.stateful:
            out["comm_e"] = new_e[None]
        return out, lax.pmean(loss, axis)

    batch_spec = make_spec(axis)

    # shard_map specs are positional; rebuild per-call for variadic batches
    @functools.lru_cache(maxsize=8)
    def _compiled(n_batch):
        w = _shard_map(
            body, mesh=mesh,
            in_specs=(state_specs, replicated_spec()) + (batch_spec,) * n_batch,
            out_specs=(state_specs, replicated_spec()),
            # non-fp32: the quantized exchange rebuilds values from
            # all_to_all'd payloads the VMA checker cannot statically prove
            # replicated (same rationale as dgc.py's scatter-add)
            check_vma=False if policy.name != "fp32" else None)
        return jax.jit(w, donate_argnums=(0,) if donate else ())

    def step(state, lr, *batch):
        return _compiled(len(batch))(state, jnp.asarray(lr, jnp.float32),
                                     *batch)

    from ..telemetry import instrument_train_step
    comm = comm_info(params0, policy)
    if comm is not None:
        # the exchange only runs every k_steps-th call: amortize the
        # per-sync estimate so per-step comm events stay truthful (the
        # savings ratio is unchanged)
        comm = dict(comm, pre_bytes=comm["pre_bytes"] // k_steps,
                    post_bytes=max(comm["post_bytes"] // k_steps, 1))
    return instrument_train_step(step, monitor, "localsgd", comm=comm), state0
