"""LocalSGD (reference: fleet/meta_optimizers/localsgd_optimizer.py:26).

The reference rewrites the program so each data-parallel worker trains on
its own gradient for ``k_steps`` and then block-averages the parameters
(c_allreduce on params, not grads).  TPU-native formulation: parameters and
optimizer slots carry a leading replica dimension sharded over the data
axis; the whole schedule — local grad, local update, every-k parameter
average — runs inside ONE ``shard_map``-wrapped jitted step, with the sync
point expressed as a ``lax.cond`` on the step counter so there is no host
control flow and the collective is genuinely skipped at runtime on
non-sync steps (the entire point of LocalSGD: ICI traffic drops by ~k×).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .spmd import shard_map as _shard_map

__all__ = ["make_localsgd_train_step"]


def make_localsgd_train_step(loss_of: Callable, params0: Dict[str, Any],
                             optimizer, mesh: Mesh, k_steps: int = 4,
                             axis: str = "data", donate: bool = True,
                             monitor=None):
    """Build a LocalSGD step over the ``axis`` mesh axis.

    ``loss_of(params, *batch) -> scalar``; ``batch`` leading dim is the
    global batch, split evenly over ``axis``.  Returns ``(step, state0)``
    with ``step(state, lr, *batch) -> (state, loss)`` where loss is the
    cross-replica mean of the local losses.  Parameters are kept per-replica
    (leading dim R, sharded on ``axis``) and block-averaged every
    ``k_steps``-th call; reading them out: ``state["params"]`` rows are
    identical right after a sync step.
    """
    R = mesh.shape[axis]
    if k_steps < 1:
        raise ValueError(f"k_steps must be >= 1, got {k_steps}")

    stack = lambda p: jnp.broadcast_to(p[None], (R,) + p.shape)
    params_r = jax.tree_util.tree_map(stack, params0)
    opt_r = jax.tree_util.tree_map(stack, optimizer.init_state(params0))
    state0 = {"params": params_r, "opt": opt_r,
              "count": jnp.zeros([], jnp.int32)}

    rep_spec = lambda leaf: P(axis, *([None] * (np.ndim(leaf) - 1)))
    state_specs = {
        "params": jax.tree_util.tree_map(rep_spec, params_r),
        "opt": jax.tree_util.tree_map(rep_spec, opt_r),
        "count": P(),
    }
    state0 = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        state0, state_specs)

    def body(state, lr, *batch):
        # inside shard_map: params/opt leaves have leading dim 1 (this
        # replica); batch rows are this replica's slice
        params = jax.tree_util.tree_map(lambda a: a[0], state["params"])
        opt = jax.tree_util.tree_map(lambda a: a[0], state["opt"])
        count = state["count"] + 1

        loss, grads = jax.value_and_grad(loss_of)(params, *batch)
        new_params, new_opt = optimizer.update(grads, opt, params, lr=lr)

        # lax.cond, NOT jnp.where: where would execute the pmean every step
        # and merely discard it — the collective must be skipped at runtime
        # on non-sync steps or LocalSGD saves no ICI traffic at all
        sync = (count % k_steps) == 0

        from .spmd import ensure_varying

        def _revary(p):
            # pmean output is replicated; the skip branch stays varying —
            # re-mark so both lax.cond branches type-check under the VMA
            # checker (the values ARE equal across replicas post-pmean)
            return ensure_varying(p, axis)

        new_params = lax.cond(
            sync,
            lambda ps: jax.tree_util.tree_map(
                lambda p: _revary(lax.pmean(p, axis)), ps),
            lambda ps: ps,
            new_params)

        out = {"params": jax.tree_util.tree_map(lambda a: a[None], new_params),
               "opt": jax.tree_util.tree_map(lambda a: a[None], new_opt),
               "count": count}
        return out, lax.pmean(loss, axis)

    batch_spec = P(axis)

    # shard_map specs are positional; rebuild per-call for variadic batches
    @functools.lru_cache(maxsize=8)
    def _compiled(n_batch):
        w = _shard_map(
            body, mesh=mesh,
            in_specs=(state_specs, P()) + (batch_spec,) * n_batch,
            out_specs=(state_specs, P()))
        return jax.jit(w, donate_argnums=(0,) if donate else ())

    def step(state, lr, *batch):
        return _compiled(len(batch))(state, jnp.asarray(lr, jnp.float32),
                                     *batch)

    from ..telemetry import instrument_train_step
    return instrument_train_step(step, monitor, "localsgd"), state0
