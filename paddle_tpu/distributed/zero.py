"""ZeRO sharding (stages 1-3) with contractual semantics.

Reference capabilities being matched (TPU-natively, not by program surgery):
- fleet/meta_optimizers/sharding_optimizer.py:45 — the 1800-line static-graph
  ZeRO surgeon (_split_program:803, _prune_main_program:936,
  _add_broadcast_allreduce:1045) → sharding annotations + GSPMD.
- dygraph_optimizer/sharding_optimizer_stage2.py:46 + internal_storage.py:28 —
  rank-aligned fused grad/param buffers → NamedSharding over the "sharding"
  mesh axis (XLA lays out and fuses; alignment is the compiler's job).
- hybrid_parallel_optimizer.py:173 — found_inf / global-norm-clip / update
  ordering under hybrid parallelism.
- operators/amp/check_finite_and_unscale_op.cc + update_loss_scaling_op.cc —
  dynamic loss scaling semantics.

The contract per stage (all under one jit; XLA emits the collectives):
- stage 1: optimizer state (slots + fp32 master weights) sharded 1/N over
  the "sharding" axis.
- stage 2: + gradients reduce-scattered: the grad pytree is constrained to
  the slot sharding right after value_and_grad, so the data-parallel
  reduction becomes reduce_scatter over the axis instead of all_reduce.
- stage 3: + parameters stored sharded; gathered on use (GSPMD inserts
  all-gathers at the consuming matmuls and frees them after — the
  gather/release schedule the reference implements by hand).

Update ordering (one step): scaled loss → grads → unscale → found_inf (any
non-finite, global) → [optimizer's global-norm clip] → update → select
old/new state by found_inf → loss-scale update.  The step counter and
loss-scale bookkeeping only advance on finite steps.

Tensors with no dimension divisible by the sharding degree stay replicated
and are WARNED about with a byte count (reference pads to alignment,
internal_storage.py:28 — here the tradeoff is explicit instead of silent).
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from .sharding_rules import (_slot_spec, build_param_specs,
                             replicated_spec, resolve_flat_shard_spec)

_HALF_DTYPES = (jnp.bfloat16, jnp.float16)


def _warn_unsharded(kind: str, failures, degree: int):
    if not failures:
        return
    total = sum(b for _, b in failures)
    names = ", ".join(n for n, _ in failures[:5])
    warnings.warn(
        f"ZeRO: {len(failures)} {kind} tensor(s) have no dim divisible by "
        f"sharding degree {degree} and stay fully replicated "
        f"({total / 1e6:.2f} MB per device): {names}"
        + (", ..." if len(failures) > 5 else ""))


def zero_state_specs(params0: Dict[str, Any], mesh: Mesh, layer=None,
                     zero_stage: int = 1):
    """(param_specs, slot_specs) for the stage, with replication accounting."""
    p_specs = build_param_specs(params0, mesh, layer, zero_stage)
    s_specs = {k: _slot_spec(p_specs[k], p, mesh, max(zero_stage, 1))
               for k, p in params0.items()}
    deg = mesh.shape.get("sharding", 1)
    if deg > 1:
        def nbytes(p):
            return int(jnp.size(p)) * jnp.dtype(p.dtype).itemsize
        _warn_unsharded("optimizer-state", [
            (k, nbytes(p)) for k, p in params0.items()
            if "sharding" not in s_specs[k]], deg)
        if zero_stage >= 3:
            _warn_unsharded("parameter", [
                (k, nbytes(p)) for k, p in params0.items()
                if "sharding" not in p_specs[k]], deg)
    return p_specs, s_specs


def make_zero_train_step(loss_of: Callable, params0: Dict[str, Any], optimizer,
                         mesh: Mesh, layer=None, zero_stage: int = 1,
                         master_weights: Optional[bool] = None,
                         dynamic_loss_scale: bool = False,
                         init_loss_scale: float = 2.0 ** 15,
                         growth_interval: int = 1000,
                         backoff_factor: float = 0.5,
                         growth_factor: float = 2.0,
                         donate: bool = True,
                         offload: bool = False,
                         monitor=None,
                         grad_comm=None):
    """Build the sharded train step.

    ``loss_of(params, *batch) -> scalar``.  Returns ``(step, state0)`` with
    ``step(state, lr, *batch) -> (state, loss)``.  state = {params, opt,
    master, scaler}; scaler = {scale, good_steps, found_inf} (found_inf from
    the LAST step, for GradScaler-style inspection).

    ``monitor``: optional ``telemetry.TrainMonitor`` — wraps the returned
    step with host-side timing outside the jit boundary (compiled program
    identical either way; ``None`` returns the bare step).

    ``grad_comm``: gradient-communication policy (``"fp32"`` default /
    ``"bf16"`` / ``"int8_ef"`` / a ``grad_comm.GradCommPolicy``), applied
    to the unscaled fp32 gradients RIGHT BEFORE the stage-2 sharding
    constraint — the reduce-scatter seam — so the value GSPMD scatters is
    the policy's compressed-then-decompressed gradient.  On this GSPMD
    path XLA owns the collective schedule, so the policy governs numerics
    + byte accounting; the true int8-hop composition lives in the
    shard_map trainers (docs/DISTRIBUTED_COMM.md).  Stateful policies add
    a flat ``"comm_e"`` error-feedback residual to the state, sharded
    over the "sharding" axis when divisible.

    ``offload=True`` (≙ sharding_configs offload) routes through
    ``make_zero_offload_train_step``: optimizer slots + masters in host
    memory, update on the host CPU backend (no dynamic loss scaling there —
    offload targets memory-bound fp32/bf16 runs).
    """
    from .grad_comm import apply_policy_local, comm_info, resolve_policy
    policy = resolve_policy(grad_comm)
    if offload and policy.name != "fp32":
        raise NotImplementedError(
            "offload=True with grad_comm != 'fp32' is not wired: the "
            "offload path's wire is PCIe (host<->device), not ICI — "
            "compressing it is a different policy axis")
    if offload:
        if dynamic_loss_scale:
            raise NotImplementedError(
                "offload=True with dynamic_loss_scale is not supported; "
                "use static scaling (the offload path keeps found_inf "
                "skip-update semantics)")
        return make_zero_offload_train_step(
            loss_of, params0, optimizer, mesh, layer=layer,
            zero_stage=zero_stage, master_weights=master_weights,
            monitor=monitor)
    if master_weights is None:
        master_weights = any(p.dtype in _HALF_DTYPES
                             for p in jax.tree_util.tree_leaves(params0))

    p_specs, s_specs = zero_state_specs(params0, mesh, layer, zero_stage)
    # fp32 masters ONLY for half-precision params (reference multi_precision
    # semantics) — duplicating already-fp32 tensors would double their memory
    half_keys = {k for k, p in params0.items() if p.dtype in _HALF_DTYPES} \
        if master_weights else set()
    master0 = {k: params0[k].astype(jnp.float32) for k in half_keys}
    # slots track the update-precision copy (fp32 master where one exists)
    upd_params0 = {k: master0.get(k, p) for k, p in params0.items()}
    opt_state0 = optimizer.init_state(upd_params0)
    scaler0 = {
        "scale": jnp.asarray(init_loss_scale if dynamic_loss_scale else 1.0,
                             jnp.float32),
        "good_steps": jnp.zeros([], jnp.int32),
        "found_inf": jnp.zeros([], jnp.bool_),
    }
    state0 = {"params": params0, "opt": opt_state0, "master": master0,
              "scaler": scaler0}
    if policy.stateful:
        state0["comm_e"] = policy.residual_for(params0)

    rep = NamedSharding(mesh, replicated_spec())
    p_sh = {k: NamedSharding(mesh, p_specs[k]) for k in params0}
    s_sh = {k: NamedSharding(mesh, s_specs[k]) for k in params0}

    def slot_tree_sh(slots_of_param, k):
        return {sn: (s_sh[k] if hasattr(v, "shape") and v.ndim > 0 else rep)
                for sn, v in slots_of_param.items()}

    state_sh = {
        "params": p_sh,
        "opt": {"step": rep,
                "slots": {k: slot_tree_sh(v, k)
                          for k, v in state0["opt"]["slots"].items()}},
        "master": {k: s_sh[k] for k in master0},
        "scaler": {k: rep for k in scaler0},
    }
    if policy.stateful:
        # flat EF residual rides the "sharding" axis when divisible (block
        # padding makes power-of-two degrees always divide); an indivisible
        # length degrades to replication WITH byte accounting
        # (resolve_flat_shard_spec warns + bumps
        # sharding_replicated_fallback_bytes — never silently)
        state_sh["comm_e"] = NamedSharding(
            mesh, resolve_flat_shard_spec(
                "comm_e", int(state0["comm_e"].shape[0]), mesh, "sharding",
                tracer=getattr(monitor, "tracer", None)))

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state, lr, *batch):
        scale = state["scaler"]["scale"]

        def scaled_loss(p):
            return loss_of(p, *batch) * scale

        loss_s, grads = jax.value_and_grad(scaled_loss)(state["params"])
        loss = loss_s / scale
        inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads)

        # found_inf BEFORE clip (check_finite_and_unscale ordering), and
        # before grad-comm compression (quantizing a non-finite tree is
        # undefined; the step is skipped either way)
        found_inf = functools.reduce(
            jnp.logical_or,
            [jnp.any(~jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)],
            jnp.zeros([], jnp.bool_))

        # the reduce-scatter seam: compress here so the value the stage-2
        # constraint scatters is the policy's dequantized grad
        grads, comm_state = apply_policy_local(policy, grads, state,
                                               found_inf=found_inf)
        if zero_stage >= 2:
            # stage-2 contract: gradients land reduce-scattered over the
            # sharding axis (GSPMD turns the dp reduction + this constraint
            # into reduce_scatter; ≙ ShardingOptimizerStage2 grad buckets)
            grads = {k: jax.lax.with_sharding_constraint(
                g, s_sh[k]) for k, g in grads.items()}

        upd_params = {k: state["master"].get(k, p)
                      for k, p in state["params"].items()}
        new_upd, new_opt = optimizer.update(grads, state["opt"], upd_params, lr=lr)

        def sel(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(found_inf, o, n), new, old)

        new_upd = sel(new_upd, upd_params)
        new_opt = {"step": jnp.where(found_inf, state["opt"]["step"],
                                     new_opt["step"]),
                   "slots": sel(new_opt["slots"], state["opt"]["slots"])}

        new_master = {k: jax.lax.with_sharding_constraint(new_upd[k], s_sh[k])
                      for k in half_keys}
        new_params = {k: (new_master[k].astype(params0[k].dtype)
                          if k in half_keys else new_upd[k])
                      for k in new_upd}
        new_params = {k: jax.lax.with_sharding_constraint(v, p_sh[k])
                      for k, v in new_params.items()}

        if dynamic_loss_scale:
            good = jnp.where(found_inf, 0, state["scaler"]["good_steps"] + 1)
            grow = good >= growth_interval
            new_scale = jnp.where(
                found_inf, jnp.maximum(scale * backoff_factor, 1.0),
                jnp.where(grow, scale * growth_factor, scale))
            good = jnp.where(grow, 0, good)
        else:
            new_scale, good = scale, state["scaler"]["good_steps"]

        new_state = {"params": new_params, "opt": new_opt, "master": new_master,
                     "scaler": {"scale": new_scale, "good_steps": good,
                                "found_inf": found_inf}, **comm_state}
        return new_state, loss

    state0 = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), state0, state_sh,
        is_leaf=lambda x: hasattr(x, "shape"))
    from ..telemetry import instrument_train_step
    return instrument_train_step(step, monitor, "zero",
                                 comm=comm_info(params0, policy)), state0


def make_zero_offload_train_step(loss_of: Callable, params0: Dict[str, Any],
                                 optimizer, mesh: Mesh, layer=None,
                                 zero_stage: int = 1,
                                 master_weights: Optional[bool] = None,
                                 monitor=None):
    """CPU-offload variant (≙ reference sharding_configs ``offload=True`` /
    DygraphShardingOptimizer offload): optimizer slots + fp32 masters live in
    HOST memory; each step ships fp32 grads host-ward, runs the update on the
    host CPU backend, and ships the compute-dtype params back.  Device HBM
    then holds only params + activations — the optimizer states (2× fp32 for
    Adam, + masters) move off-chip at the price of PCIe/host traffic per
    step.

    Two jitted phases orchestrated in Python (one jit cannot span backends):
      device: grads = ∇(loss·scale), found_inf, loss
      host:   (new_master/new_upd, new_opt) = optimizer.update(...)
    Returns (step, state0); state = {params(dev), opt(host), master(host),
    scaler(host)}.  step(state, lr, *batch) -> (state, loss).
    """
    del master_weights  # the offload path is always master-weighted: the
    # host keeps THE authoritative fp32 copy of every param ("master" for
    # half params, same role for fp32 params) so no step ever fetches params
    # from device — per-step traffic is exactly grads down + params up
    cpu0 = jax.devices("cpu")[0]
    p_specs, s_specs = zero_state_specs(params0, mesh, layer, zero_stage)
    p_sh = {k: NamedSharding(mesh, p_specs[k]) for k in params0}
    s_sh = {k: NamedSharding(mesh, s_specs[k]) for k in params0}

    master0 = {k: np.asarray(p, np.float32) for k, p in params0.items()}
    opt_state0 = optimizer.init_state(master0)

    host = functools.partial(jax.device_put, device=cpu0)
    state0 = {
        "params": {k: jax.device_put(v, p_sh[k]) for k, v in params0.items()},
        "opt": jax.tree_util.tree_map(host, opt_state0),
        "master": {k: host(v) for k, v in master0.items()},
        "scaler": {"scale": host(jnp.ones([], jnp.float32)),
                   "good_steps": host(jnp.zeros([], jnp.int32)),
                   "found_inf": host(jnp.zeros([], jnp.bool_))},
    }

    @jax.jit
    def grad_phase(params, *batch):
        loss, grads = jax.value_and_grad(lambda p: loss_of(p, *batch))(params)
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        if zero_stage >= 2:
            # stage-2 contract holds on the offload path too: grads land
            # reduce-scattered so peak HBM never sees the replicated tree
            grads = {k: jax.lax.with_sharding_constraint(g, s_sh[k])
                     for k, g in grads.items()}
        found_inf = functools.reduce(
            jnp.logical_or,
            [jnp.any(~jnp.isfinite(g))
             for g in jax.tree_util.tree_leaves(grads)],
            jnp.zeros([], jnp.bool_))
        return loss, grads, found_inf

    @jax.jit
    def host_phase(grads, opt, master, lr, found_inf):
        new_upd, new_opt = optimizer.update(grads, opt, master, lr=lr)

        def sel(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(found_inf, o, n), new, old)

        new_master = sel(new_upd, master)
        new_opt = {"step": jnp.where(found_inf, opt["step"], new_opt["step"]),
                   "slots": sel(new_opt["slots"], opt["slots"])}
        new_params = {k: new_master[k].astype(params0[k].dtype)
                      for k in new_master}
        return new_params, new_opt, new_master

    def step(state, lr, *batch):
        loss, grads, found_inf = grad_phase(state["params"], *batch)
        g_host = jax.tree_util.tree_map(host, grads)
        fi_host = host(found_inf)
        new_params, new_opt, new_master = host_phase(
            g_host, state["opt"], state["master"],
            host(jnp.asarray(lr, jnp.float32)), fi_host)
        new_state = {
            "params": {k: jax.device_put(v, p_sh[k])
                       for k, v in new_params.items()},
            "opt": new_opt,
            "master": new_master,
            "scaler": {"scale": state["scaler"]["scale"],
                       "good_steps": state["scaler"]["good_steps"],
                       "found_inf": fi_host},
        }
        return new_state, loss

    from ..telemetry import instrument_train_step
    return instrument_train_step(step, monitor, "zero_offload"), state0


def per_device_state_bytes(state) -> int:
    """Addressable bytes of the optimizer state (slots + master) on device 0 —
    the quantity ZeRO shrinks ~1/shard (assertion hook for tests/benchmarks)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves({"opt": state["opt"]["slots"],
                                           "master": state.get("master", {})}):
        if hasattr(leaf, "addressable_shards"):
            shard = leaf.addressable_shards[0]
            total += int(shard.data.size) * leaf.dtype.itemsize
    return total
