"""``paddle_tpu.distributed`` (reference: python/paddle/distributed/)."""

from . import checkpoint, env, fleet, utils  # noqa: F401
from .collective import (Group, ReduceOp, all_gather, all_gather_object,  # noqa: F401
                         all_reduce, alltoall, alltoall_single, barrier, broadcast,
                         broadcast_object_list, destroy_process_group, get_group,
                         irecv, is_initialized, isend, new_group, recv, reduce,
                         reduce_scatter, scatter, send, split, wait)
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env  # noqa: F401
from .fleet.meta_parallel import DataParallel  # noqa: F401
from .dgc import make_dgc_train_step  # noqa: F401
from .grad_comm import (GradCommPolicy, compressed_all_reduce,  # noqa: F401
                        compressed_reduce_scatter, resolve_policy)
from .localsgd import make_localsgd_train_step  # noqa: F401
from .sharding_rules import (ShardingRules, match_partition_rules,  # noqa: F401
                             sharding_rules_digest, spec_tree_digest)
from .spmd import make_spmd_train_step, shard_batch  # noqa: F401
from .update_sharding import make_dp_update_sharded_train_step  # noqa: F401
from .zero import make_zero_train_step, per_device_state_bytes  # noqa: F401
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Reference: distributed/spawn.py — multiprocess notebook launcher.
    On TPU single-process SPMD covers local devices; true multi-host uses
    ``paddle_tpu.distributed.launch``.  Runs ``func`` in-process when
    nprocs<=1 (device parallelism comes from the mesh)."""
    if nprocs in (-1, 0, 1):
        func(*args)
        return None
    raise NotImplementedError(
        "multi-process spawn on one host is not applicable to TPU SPMD; "
        "use paddle_tpu.distributed.launch for multi-host")


def get_backend():
    return "xla"


# -- namespace parity tail (reference distributed/__init__.py) --------------

from . import launch as launch  # noqa: F401,E402  (python -m ... entry too)
from .auto_parallel import shard_op, shard_tensor  # noqa: F401,E402
from ..io.dataset import InMemoryDataset, QueueDataset  # noqa: F401,E402


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Reference parallel_with_gloo.py — CPU rendezvous.  jax.distributed's
    coordination service fills this role; the explicit arguments are
    authoritative (they overwrite any launcher-provisioned PADDLE_* env)."""
    import os
    os.environ["PADDLE_TRAINER_ID"] = str(rank_id)
    os.environ["PADDLE_TRAINERS_NUM"] = str(rank_num)
    os.environ["PADDLE_MASTER"] = server_endpoint
    env.init_parallel_env()


def gloo_barrier():
    barrier()


def gloo_release():
    """The gloo context is the jax coordination service here; released at
    process exit (documented no-op)."""


class _EntryAttr:
    """PS sparse-table entry configs (reference entry_attr.py) — data
    holders kept for API parity; the PS runtime itself is a declared
    non-goal (SURVEY §7), so these only carry their repr contract."""

    def _to_attr(self):
        raise NotImplementedError


class ProbabilityEntry(_EntryAttr):
    def __init__(self, probability):
        if not 0 <= probability <= 1:
            raise ValueError("probability must be in [0, 1]")
        self._probability = probability

    def _to_attr(self):
        return f"probability_entry:{self._probability}"


class CountFilterEntry(_EntryAttr):
    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._count_filter = count_filter

    def _to_attr(self):
        return f"count_filter_entry:{self._count_filter}"


class ShowClickEntry(_EntryAttr):
    def __init__(self, show_name, click_name):
        self._show = show_name
        self._click = click_name

    def _to_attr(self):
        return f"show_click_entry:{self._show}:{self._click}"


class BoxPSDataset:
    """Heterogeneous BoxPS dataset (reference fleet/dataset) — GPU-PS
    specific; unavailable by design on TPU."""

    def __init__(self, *a, **k):
        raise RuntimeError("BoxPS is a GPU parameter-server feature; the "
                           "TPU build's dataset path is io.InMemoryDataset")


from . import cloud_utils  # noqa: F401,E402
