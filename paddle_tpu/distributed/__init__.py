"""``paddle_tpu.distributed`` (reference: python/paddle/distributed/)."""

from . import checkpoint, env, fleet, utils  # noqa: F401
from .collective import (Group, ReduceOp, all_gather, all_gather_object,  # noqa: F401
                         all_reduce, alltoall, alltoall_single, barrier, broadcast,
                         broadcast_object_list, destroy_process_group, get_group,
                         irecv, is_initialized, isend, new_group, recv, reduce,
                         reduce_scatter, scatter, send, split, wait)
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env  # noqa: F401
from .fleet.meta_parallel import DataParallel  # noqa: F401
from .dgc import make_dgc_train_step  # noqa: F401
from .localsgd import make_localsgd_train_step  # noqa: F401
from .spmd import make_spmd_train_step, shard_batch  # noqa: F401
from .zero import make_zero_train_step, per_device_state_bytes  # noqa: F401
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Reference: distributed/spawn.py — multiprocess notebook launcher.
    On TPU single-process SPMD covers local devices; true multi-host uses
    ``paddle_tpu.distributed.launch``.  Runs ``func`` in-process when
    nprocs<=1 (device parallelism comes from the mesh)."""
    if nprocs in (-1, 0, 1):
        func(*args)
        return None
    raise NotImplementedError(
        "multi-process spawn on one host is not applicable to TPU SPMD; "
        "use paddle_tpu.distributed.launch for multi-host")


def get_backend():
    return "xla"
