"""Deep Gradient Compression (reference: DGCMomentumOptimizer in
fluid/optimizer.py + operators/dgc_op.h — top-k gradient sparsification
with momentum correction and local gradient accumulation, Lin et al. 2018).

Per parameter, per step (the reference kernel's recurrence):

    u = m * u + g            (momentum correction)
    v = v + u                (local accumulation)
    send top-k |v| entries;  clear u, v at the selected coordinates

TPU-native exchange: the k surviving (value, index) pairs per replica ride
ONE ``all_gather`` over the data axis — 2k elements instead of n, which is
the actual compression (a masked dense psum would move n elements and
compress nothing).  The gathered pairs scatter-add into a dense buffer that
feeds the wrapped optimizer.  Before ``rampup_begin_step`` the step runs a
plain dense ``pmean`` (the reference's warm-up).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding

from .grad_comm import ef_accumulate, ef_residual
from .sharding_rules import make_spec, replica_stacked_spec, replicated_spec
from .spmd import shard_map as _shard_map

__all__ = ["make_dgc_train_step"]


def _topk_compress(v, k):
    """(values, int32 indices) of the k largest-|v| entries of flat v."""
    mag = jnp.abs(v)
    _, idx = lax.top_k(mag, k)
    vals = v[idx]
    return vals, idx.astype(jnp.int32)


def make_dgc_train_step(loss_of: Callable, params0: Dict[str, Any], optimizer,
                        mesh: Mesh, sparsity: float = 0.999,
                        momentum: float = 0.9, rampup_begin_step: int = 0,
                        axis: str = "data", donate: bool = True,
                        monitor=None):
    """Build a data-parallel step with DGC gradient exchange.

    ``loss_of(params, *batch) -> scalar``; batch splits over ``axis``.
    Returns ``(step, state0)``; ``step(state, lr, *batch) -> (state, loss)``.
    state = {params, opt, u, v, count}: params/opt replicated, u/v carry a
    leading per-replica dim sharded on ``axis`` (each replica owns its
    residuals, exactly the reference's local accumulators).
    """
    R = mesh.shape[axis]
    if not 0.0 <= sparsity < 1.0:
        raise ValueError(f"sparsity must be in [0, 1), got {sparsity}")

    flat_sizes = {k: int(np.prod(p.shape)) for k, p in params0.items()}
    ks = {k: max(1, int(round(n * (1.0 - sparsity))))
          for k, n in flat_sizes.items()}

    stack = lambda p: jnp.zeros((R,) + p.shape, jnp.float32)
    state0 = {
        "params": params0,
        "opt": optimizer.init_state(params0),
        "u": jax.tree_util.tree_map(stack, params0),
        "v": jax.tree_util.tree_map(stack, params0),
        "count": jnp.zeros([], jnp.int32),
    }
    rep_spec = lambda leaf: replicated_spec()
    resid_spec = lambda leaf: replica_stacked_spec(leaf, axis)
    specs = {
        "params": jax.tree_util.tree_map(rep_spec, state0["params"]),
        "opt": jax.tree_util.tree_map(rep_spec, state0["opt"]),
        "u": jax.tree_util.tree_map(resid_spec, state0["u"]),
        "v": jax.tree_util.tree_map(resid_spec, state0["v"]),
        "count": replicated_spec(),
    }
    state0 = jax.tree_util.tree_map(
        lambda leaf, sp: jax.device_put(leaf, NamedSharding(mesh, sp)),
        state0, specs)

    def body(state, lr, *batch):
        params = state["params"]
        u = jax.tree_util.tree_map(lambda a: a[0], state["u"])
        v = jax.tree_util.tree_map(lambda a: a[0], state["v"])
        count = state["count"] + 1

        loss, grads = jax.value_and_grad(loss_of)(params, *batch)

        def compress_one(name, g, u1, v1):
            n = flat_sizes[name]
            k = ks[name]
            gf = g.reshape(-1).astype(jnp.float32)

            def dgc_branch(args):
                gf_, u_, v_ = args
                u2 = momentum * u_ + gf_
                # error-feedback accumulate/clear via the SHARED grad_comm
                # helpers (one implementation with int8_ef, so the two
                # compressed exchanges cannot drift): v = v + u, and the
                # residual keeps v minus the decompressed payload — for
                # top-k that is exactly "clear the sent coordinates"
                # (v2 - v2[idx] == 0 there, bit-identical to .at[].set(0))
                v2 = ef_accumulate(v_, u2)
                vals, idx = _topk_compress(v2, k)
                local_sent = jnp.zeros_like(v2).at[idx].set(vals)
                sent_mask = jnp.zeros_like(v2).at[idx].set(1.0)
                # the where pins sent coordinates to exactly 0.0 even for
                # non-finite entries (v2 - v2 would be NaN for inf), which
                # is the reference kernel's clear semantics
                u3 = jnp.where(sent_mask > 0, 0.0, u2)
                v3 = jnp.where(sent_mask > 0, 0.0,
                               ef_residual(v2, local_sent))
                # exchange 2k elements: all replicas' (vals, idx)
                all_vals = lax.all_gather(vals, axis)      # (R, k)
                all_idx = lax.all_gather(idx, axis)        # (R, k)
                dense = jnp.zeros((n,), jnp.float32).at[
                    all_idx.reshape(-1)].add(all_vals.reshape(-1)) / R
                return dense, u3, v3

            def warm_branch(args):
                gf_, u_, v_ = args
                from .spmd import ensure_varying
                # replicated warm-up outputs vs varying DGC-branch residuals:
                # unify variance for the cond type check
                return tuple(ensure_varying(o, axis) for o in
                             (lax.pmean(gf_, axis), jnp.zeros_like(u_),
                              jnp.zeros_like(v_)))

            # lax.cond so the non-taken branch's collective is skipped at
            # runtime (jnp.where would run the dense pmean every step)
            g_out, u_out, v_out = lax.cond(
                count <= rampup_begin_step, warm_branch, dgc_branch,
                (gf, u1.reshape(-1), v1.reshape(-1)))
            return (g_out.reshape(g.shape).astype(g.dtype),
                    u_out.reshape(g.shape), v_out.reshape(g.shape))

        agg, new_u, new_v = {}, {}, {}
        for name in params:
            agg[name], new_u[name], new_v[name] = compress_one(
                name, grads[name], u[name], v[name])

        new_params, new_opt = optimizer.update(agg, state["opt"], params, lr=lr)
        out = {
            "params": new_params, "opt": new_opt,
            "u": jax.tree_util.tree_map(lambda a: a[None], new_u),
            "v": jax.tree_util.tree_map(lambda a: a[None], new_v),
            "count": count,
        }
        return out, lax.pmean(loss, axis)

    @functools.lru_cache(maxsize=8)
    def _compiled(n_batch):
        # check_vma stays off here: the aggregated gradient is built by
        # scattering all_gather'd (vals, idx) pairs — value-identical on
        # every replica, but the VMA checker cannot statically prove
        # replication through a scatter, so P() out_specs would be rejected
        w = _shard_map(
            body, mesh=mesh,
            in_specs=(specs, replicated_spec()) + (make_spec(axis),) * n_batch,
            out_specs=(specs, replicated_spec()),
            check_vma=False)
        return jax.jit(w, donate_argnums=(0,) if donate else ())

    def step(state, lr, *batch):
        return _compiled(len(batch))(state, jnp.asarray(lr, jnp.float32),
                                     *batch)

    from ..telemetry import instrument_train_step
    return instrument_train_step(step, monitor, "dgc"), state0
