"""Distributed (sharded) checkpointing.

Reference counterparts: the sharded save/load surgeons in
fleet/meta_optimizers/sharding_optimizer.py (+ fleet/utils/internal_storage.py
buffer slicing) and the >4GB-aware single-file path in
python/paddle/framework/io.py:553.  TPU-native design: a checkpoint is a
directory of per-shard ``.npy`` chunks plus one JSON manifest describing the
global pytree — no pickled objects, no host gather of the full state.

Key properties:

- **Per-host shard save.** Every process writes only the array shards it
  addresses (``arr.addressable_shards``), deduplicated by ``replica_id == 0``
  so replicated values are stored once per replica group.  A multi-host job
  on a shared filesystem therefore writes each byte exactly once.
- **Resume on a different mesh.** Loading assembles each leaf with
  ``jax.make_array_from_callback`` against the *new* sharding: each device
  reads only the chunk ranges overlapping its shard (numpy ``mmap_mode`` —
  no full-array materialization), which is the elastic rescale story
  (fleet/elastic.py): save on dp8, resume on dp4.
- **>4GB safety.** Leaves are split into chunks of at most
  ``_MAX_CHUNK_BYTES`` along their largest dimension, so no single file and
  no single host buffer exceeds the cap (the reference splits pickles the
  same way at framework/io.py:553).
- **Async save.** ``save(..., async_save=True)`` snapshots device arrays to
  host (the only synchronous part) and runs the file writes on a background
  thread; the returned handle's ``.wait()``/``.result()`` joins.
"""

from __future__ import annotations

import concurrent.futures as _futures
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_MANIFEST = "manifest.json"
_MAX_CHUNK_BYTES = 2 << 30  # 2 GiB per chunk file


class CorruptCheckpoint(RuntimeError):
    """The checkpoint directory is structurally damaged: a chunk file is
    missing, truncated, or disagrees with the manifest's shape/dtype, or
    the manifest itself is unreadable.  A crash between the per-file
    ``os.replace`` calls and the manifest replace leaves exactly this
    shape (old-manifest + new files, or manifest referencing files that
    never landed) — ``load()`` raises this instead of returning silently
    wrong arrays.  ``train_resilience.CheckpointManager`` catches it to
    fall back to the previous committed step."""


def _storage_dtype(dtype: np.dtype) -> Optional[np.dtype]:
    """Raw-bytes storage dtype for numpy *extension* dtypes (bfloat16,
    fp8 — anything ml_dtypes registers with kind ``'V'``).  ``np.save``
    writes those with an opaque ``|V2``-style descr that round-trips as
    void and breaks comparisons on load, so chunks are stored viewed as
    same-width unsigned ints and viewed back on read; the manifest keeps
    the logical dtype name."""
    if dtype.kind == "V" and dtype.itemsize in (1, 2, 4, 8):
        return np.dtype(f"u{dtype.itemsize}")
    return None


# --------------------------------------------------------------------------
# pytree <-> flat {key: leaf}
# --------------------------------------------------------------------------

def _flatten(tree) -> Dict[str, Any]:
    flat = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k in sorted(node):
                walk(f"{prefix}/{k}" if prefix else str(k), node[k])
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(f"{prefix}/{i}", v)
        else:
            flat[prefix] = node

    walk("", tree)
    return flat


def _unflatten_into(template, values: Dict[str, Any]):
    def walk(prefix, node):
        if isinstance(node, dict):
            return {k: walk(f"{prefix}/{k}" if prefix else str(k), v)
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            t = type(node)
            return t(walk(f"{prefix}/{i}", v) for i, v in enumerate(node))
        return values[prefix]

    return walk("", template)


def _safe(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", key)


# --------------------------------------------------------------------------
# save
# --------------------------------------------------------------------------

def _box(index: Tuple[slice, ...], shape) -> List[List[int]]:
    """Concrete [start, stop] per dim for an addressable-shard index."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    if not out:  # scalar
        return []
    return out


def _chunks_of(box: List[List[int]], itemsize: int):
    """Split a box into sub-boxes of at most _MAX_CHUNK_BYTES each, cutting
    along the largest dim."""
    sizes = [b[1] - b[0] for b in box]
    nbytes = int(np.prod(sizes)) * itemsize if sizes else itemsize
    if nbytes <= _MAX_CHUNK_BYTES or not sizes:
        return [box]
    d = int(np.argmax(sizes))
    n = sizes[d]
    pieces = int(np.ceil(nbytes / _MAX_CHUNK_BYTES))
    step = max(1, (n + pieces - 1) // pieces)
    out = []
    for s in range(box[d][0], box[d][1], step):
        sub = [list(b) for b in box]
        sub[d] = [s, min(s + step, box[d][1])]
        out.extend(_chunks_of(sub, itemsize))
    return out


class SaveHandle:
    """Join handle for an (optionally async) save."""

    def __init__(self, future: Optional[_futures.Future] = None):
        self._future = future

    def wait(self):
        if self._future is not None:
            self._future.result()

    result = wait

    def done(self) -> bool:
        return self._future is None or self._future.done()


_executor: Optional[_futures.ThreadPoolExecutor] = None


def _get_executor() -> _futures.ThreadPoolExecutor:
    global _executor
    if _executor is None:
        _executor = _futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="ckpt-save")
    return _executor


def save(state, path: str, async_save: bool = False,
         process_index: Optional[int] = None) -> SaveHandle:
    """Save a (possibly sharded) pytree of arrays under directory ``path``.

    Every process calls this; each writes only its addressable, replica-0
    shards plus (process 0 only) the manifest.  Returns a
    :class:`SaveHandle`; with ``async_save=True`` file writes happen on a
    background thread after a synchronous device→host snapshot.

    The SYNCHRONOUS wall (snapshot, plus the file writes unless
    ``async_save``) reports to the active goodput ledger as
    ``checkpoint_save`` — background writes overlap training and do not
    cost goodput, so they are deliberately outside the span.
    """
    from ..telemetry_ledger import ledger_span
    with ledger_span("checkpoint_save"):
        return _save_impl(state, path, async_save, process_index)


def _save_impl(state, path: str, async_save: bool,
               process_index: Optional[int]) -> SaveHandle:
    flat = _flatten(state)
    pidx = jax.process_index() if process_index is None else process_index
    os.makedirs(path, exist_ok=True)

    manifest = {"leaves": {}, "format": 1,
                "process_count": jax.process_count()}
    writes = []  # (filename, np.ndarray)
    for key, leaf in flat.items():
        if leaf is None:
            manifest["leaves"][key] = {"kind": "none"}
            continue
        arr = getattr(leaf, "_data", leaf)
        if not hasattr(arr, "shape"):
            manifest["leaves"][key] = {"kind": "py", "value": leaf}
            continue
        arr = jnp.asarray(arr) if not isinstance(arr, (jax.Array, np.ndarray)) else arr
        entry = {"kind": "array", "shape": list(np.shape(arr)),
                 "dtype": str(np.dtype(arr.dtype)), "chunks": []}
        itemsize = np.dtype(arr.dtype).itemsize
        if isinstance(arr, jax.Array) and not arr.is_fully_replicated \
                and hasattr(arr, "addressable_shards"):
            shards = [(s.index, s.data, s.replica_id)
                      for s in arr.addressable_shards]
        else:
            full = (slice(None),) * np.ndim(arr)
            rep_id = 0 if pidx == 0 else 1  # only proc 0 writes replicated leaves
            shards = [(full, np.asarray(arr), rep_id)]
        seen_boxes = set()
        for index, data, replica_id in shards:
            if replica_id != 0:
                continue
            box = _box(index, np.shape(arr))
            bkey = json.dumps(box)
            if bkey in seen_boxes:
                continue
            seen_boxes.add(bkey)
            host = np.asarray(data)
            for chunk in _chunks_of(box, itemsize):
                rel = [[c[0] - b[0], c[1] - b[0]]
                       for c, b in zip(chunk, box)]
                sub = host[tuple(slice(r[0], r[1]) for r in rel)] \
                    if rel else host
                fname = (f"{_safe(key)}." +
                         "_".join(f"{c[0]}-{c[1]}" for c in chunk) +
                         f".p{pidx}.npy") if chunk else f"{_safe(key)}.scalar.p{pidx}.npy"
                entry["chunks"].append({"file": fname, "box": chunk})
                data = np.ascontiguousarray(sub)
                st = _storage_dtype(data.dtype)
                writes.append((fname, data.view(st) if st is not None else data))
        manifest["leaves"][key] = entry

    def do_writes():
        if pidx == 0:
            # drop partial manifests from a previous save to this directory:
            # a re-save with fewer processes (elastic rescale) must not leave
            # stale chunk lists that _merged_manifest would fold back in
            for fname in os.listdir(path):
                if fname.startswith("manifest.p") and fname.endswith(".json"):
                    os.remove(os.path.join(path, fname))
        for fname, data in writes:
            # tmp name must end in .npy or np.save appends the suffix itself
            tmp = os.path.join(path, fname[:-4] + ".tmp.npy")
            np.save(tmp, data, allow_pickle=False)
            os.replace(tmp, os.path.join(path, fname))
        if pidx == 0:
            # manifest commits the checkpoint; merge chunk lists written by
            # other processes (shared FS) if their partial manifests exist
            tmp = os.path.join(path, _MANIFEST + ".tmp")
            with open(tmp, "w") as f:
                json.dump(manifest, f)
            os.replace(tmp, os.path.join(path, _MANIFEST))
        else:
            part = os.path.join(path, f"manifest.p{pidx}.json")
            with open(part + ".tmp", "w") as f:
                json.dump(manifest, f)
            os.replace(part + ".tmp", part)

    if async_save:
        return SaveHandle(_get_executor().submit(do_writes))
    do_writes()
    return SaveHandle()


# --------------------------------------------------------------------------
# load
# --------------------------------------------------------------------------

def _merged_manifest(path: str) -> Dict:
    try:
        with open(os.path.join(path, _MANIFEST)) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        raise CorruptCheckpoint(
            f"checkpoint {path!r} has no {_MANIFEST} — save never "
            f"committed (crash before the manifest replace?)")
    except (json.JSONDecodeError, OSError) as e:
        raise CorruptCheckpoint(
            f"checkpoint {path!r} manifest is unreadable: {e}")
    # multi-host: fold in per-process chunk lists — only from processes that
    # were part of THIS save's cohort (stale partials past process_count are
    # leftovers from an earlier larger-world save)
    nproc = int(manifest.get("process_count", 1))
    for fname in sorted(os.listdir(path)):
        if fname.startswith("manifest.p") and fname.endswith(".json"):
            part_idx = int(re.match(r"manifest\.p(\d+)\.json", fname).group(1))
            if part_idx >= nproc:
                continue
            with open(os.path.join(path, fname)) as f:
                part = json.load(f)
            for key, entry in part["leaves"].items():
                if entry.get("kind") == "array":
                    base = manifest["leaves"].setdefault(key, dict(entry, chunks=[]))
                    known = {json.dumps(c["box"]) for c in base["chunks"]}
                    for c in entry["chunks"]:
                        if json.dumps(c["box"]) not in known:
                            base["chunks"].append(c)
    return manifest


def _load_chunk(path: str, chunk: Dict, entry: Dict) -> np.ndarray:
    """mmap one chunk file, verifying it structurally matches what the
    manifest promised.  A crash between array writes and the manifest
    replace yields old-manifest/new-file (or manifest/no-file) mixes —
    every mismatch raises :class:`CorruptCheckpoint`, never returns
    silently wrong data."""
    fname = chunk["file"]
    try:
        src = np.load(os.path.join(path, fname), mmap_mode="r",
                      allow_pickle=False)
    except FileNotFoundError:
        raise CorruptCheckpoint(
            f"chunk file {fname!r} referenced by the manifest is missing")
    except (ValueError, OSError, EOFError) as e:
        raise CorruptCheckpoint(
            f"chunk file {fname!r} is torn/unreadable: {e}")
    logical = np.dtype(entry["dtype"])
    if src.dtype != logical:
        # extension dtypes (bf16/fp8) are stored as same-width uints
        # (legacy checkpoints: as raw void) — view back to the logical
        # dtype; any OTHER mismatch is corruption
        if logical.kind == "V" and src.dtype.itemsize == logical.itemsize:
            src = src.view(logical)
        else:
            raise CorruptCheckpoint(
                f"chunk file {fname!r} has dtype {src.dtype}, manifest "
                f"says {logical} — torn save (mixed-version directory)")
    expect = tuple(c[1] - c[0] for c in chunk["box"])
    if tuple(src.shape) != expect and not (
            expect == () and tuple(src.shape) == (1,)):
        # mmap_mode promotes 0-d arrays to shape (1,) — not corruption
        raise CorruptCheckpoint(
            f"chunk file {fname!r} has shape {tuple(src.shape)}, manifest "
            f"box {chunk['box']} expects {expect} — torn save "
            f"(mixed-version directory)")
    return src


def _read_region(path: str, entry: Dict, want: Tuple[slice, ...]) -> np.ndarray:
    """Assemble the requested region of a leaf from its chunk files (mmap —
    reads only the overlapping ranges)."""
    shape = entry["shape"]
    wbox = _box(want, shape)
    sizes = [b[1] - b[0] for b in wbox]
    out = np.empty(sizes, dtype=np.dtype(entry["dtype"]))
    filled = np.zeros(sizes, dtype=bool) if sizes else np.zeros((), bool)
    for chunk in entry["chunks"]:
        cbox = chunk["box"]
        if not cbox:  # scalar
            out[...] = _load_chunk(path, chunk, entry)
            return out
        inter = [[max(c[0], w[0]), min(c[1], w[1])]
                 for c, w in zip(cbox, wbox)]
        if any(i[0] >= i[1] for i in inter):
            continue
        src = _load_chunk(path, chunk, entry)
        src_sl = tuple(slice(i[0] - c[0], i[1] - c[0])
                       for i, c in zip(inter, cbox))
        dst_sl = tuple(slice(i[0] - w[0], i[1] - w[0])
                       for i, w in zip(inter, wbox))
        out[dst_sl] = src[src_sl]
        filled[dst_sl] = True
    if sizes and not filled.all():
        raise CorruptCheckpoint(
            f"checkpoint region {wbox} has holes — missing chunk files "
            f"(multi-host save without a shared filesystem, or a torn "
            f"multi-file save)")
    return out


def load(path: str, target=None, shardings=None):
    """Load a checkpoint directory.

    ``target``: pytree template (same structure as saved) — required.
    ``shardings``: optional matching pytree of ``jax.sharding.Sharding``;
    when given, each leaf is assembled directly into that (possibly
    different-mesh) sharding, each device reading only its own slice.
    Without it leaves load as host numpy arrays.

    Wall time reports to the active goodput ledger as
    ``checkpoint_restore``.
    """
    if target is None:
        raise ValueError("load(...) needs a target pytree template")
    from ..telemetry_ledger import ledger_span
    with ledger_span("checkpoint_restore"):
        return _load_impl(path, target, shardings)


def _load_impl(path: str, target, shardings):
    manifest = _merged_manifest(path)
    flat_t = _flatten(target)
    flat_s = _flatten(shardings) if shardings is not None else {}
    out: Dict[str, Any] = {}
    for key, tmpl in flat_t.items():
        entry = manifest["leaves"].get(key)
        if entry is None:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        kind = entry.get("kind")
        if kind == "none":
            out[key] = None
            continue
        if kind == "py":
            out[key] = entry["value"]
            continue
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        sh = flat_s.get(key)
        if sh is not None:
            arr = jax.make_array_from_callback(
                shape, sh, lambda idx, e=entry: _read_region(path, e, idx))
        else:
            arr = _read_region(path, entry, (slice(None),) * len(shape))
            tmpl_data = getattr(tmpl, "_data", tmpl)
            if isinstance(tmpl_data, jax.Array):
                arr = jnp.asarray(arr)
        out[key] = arr
    return _unflatten_into(target, out)
