"""``python -m paddle_tpu.distributed.launch`` passthrough (reference:
python -m paddle.distributed.launch)."""

from .launch import launch
import sys

sys.exit(launch())
