"""Automatic cross-replica weight-update sharding for plain data parallel.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv:2004.13336): in vanilla data parallelism every replica
all-reduces the full gradient, then runs the SAME optimizer update on the
SAME full parameter + optimizer state — R-times-redundant work holding
R-times-redundant optimizer HBM.  The paper's observation is that the
all-reduce already factors into reduce-scatter + all-gather, and the
weight update is elementwise, so it can be slid between the two halves:

    reduce-scatter grads      -> each replica owns 1/R of the mean grad
    update the 1/R shard      -> optimizer state lives ONLY as shards
    all-gather updated params -> replicas re-converge, bit-identically

Total wire bytes are unchanged (a ring all-reduce IS reduce-scatter +
all-gather); optimizer-state HBM and update-step FLOPs per replica drop
~R×.  This module implements that schedule inside one ``shard_map`` over
the replica axis, composing with the grad-comm policies of
``distributed/grad_comm.py``: ``policy.reduce_scatter`` is the seam, so
under ``int8_ef`` the only wire hop before the update is the int8
``all_to_all`` (the policy docstring calls this exact seam out) and the
error-feedback residual rides per-replica state, as in localsgd.

Array layouts come from a :class:`~.sharding_rules.ShardingRules` table
(see docs/SHARDING.md) — nothing here constructs a raw ``PartitionSpec``:

    params     -> replicated          (the model tree replicas consume)
    opt slots  -> P(axis) flat shards (the ~R× saving; scalar slot leaves
                                       like beta-power accumulators are
                                       scalar-exempt and stay replicated)
    comm_e     -> per-replica stacked (each replica's own EF residual)

The optimizer state is kept FLAT: one fused (n_pad,) vector per slot over
the whole param tree (the ``grad_comm`` flatten, zero-padded so R always
divides), because the reduce-scatter shard boundary cuts across parameter
boundaries.  ``zero.per_device_state_bytes`` measures the saving
directly; ``bench.py gpt_weight_update_sharding`` pins it ≥ 1.8× at R=2.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding

from .grad_comm import (_flatten_tree, _tree_size, _unflatten_tree,
                        comm_info, resolve_policy)
from .sharding_rules import ShardingRules, make_spec, replicated_spec
from .spmd import shard_map as _shard_map

__all__ = ["make_dp_update_sharded_train_step", "update_sharding_rules"]


def update_sharding_rules(axis: str = "data") -> ShardingRules:
    """The rule table governing this trainer's state layout (module
    docstring): flat optimizer shards and EF residuals ride the replica
    axis, everything else (model params, counters) replicates.  Scalar
    exemption keeps beta-power-style (1,) slot leaves replicated."""
    return ShardingRules(
        [
            (r"^opt/slots(/|$)", make_spec(axis)),
            (r"^comm_e$", make_spec(axis)),
            (r".*", replicated_spec()),
        ],
        unmatched="raise", name=f"dp_update_sharding[{axis}]")


def _reject_unsupported(optimizer):
    """The flat-shard update is only valid for optimizers whose functional
    update is elementwise over the parameter vector.  Refuse loudly where
    the fused flat layout would silently change semantics."""
    if getattr(optimizer, "_grad_clip", None) is not None:
        raise NotImplementedError(
            "update sharding with grad_clip: the clip norm is GLOBAL over "
            "the gradient tree, but each replica only holds a 1/R shard — "
            "computing it locally would clip by the wrong norm.  Needs a "
            "psum of the local square-sums before the clip; not wired yet.")
    if getattr(optimizer, "_wants_param_name", False) or \
            getattr(optimizer, "_per_tensor_norms", False):
        raise NotImplementedError(
            "update sharding with a per-param-identity rule (Lars/Lamb "
            "trust ratios): the fused flat shard spans parameter "
            "boundaries, so per-param norms are not computable on it.")
    if getattr(optimizer, "_multi_precision", False):
        raise NotImplementedError(
            "update sharding with multi_precision: master-weight slots "
            "need a sharded fp32 authority copy (ZeRO-style); use "
            "make_zero_train_step for that regime.")


def make_dp_update_sharded_train_step(loss_of: Callable,
                                      params0: Dict[str, Any], optimizer,
                                      mesh: Mesh, axis: str = "data",
                                      donate: bool = True, monitor=None,
                                      grad_comm=None,
                                      replicated_args: tuple = ()):
    """Build a plain-DP train step with the weight update sharded over
    ``axis`` (arXiv:2004.13336; see module docstring for the schedule).

    ``loss_of(params, *batch) -> scalar`` (mean over its batch rows);
    batch leading dims split evenly over ``axis``.  Returns
    ``(step, state0)`` with ``step(state, lr, *batch) -> (state, loss)``,
    loss being the cross-replica mean.  ``state["params"]`` is the
    ordinary replicated param tree; ``state["opt"]["slots"]["flat"]``
    holds the fused flat slot vectors, sharded 1/R per replica
    (``zero.per_device_state_bytes`` sees exactly the shard).

    ``grad_comm``: ``"fp32"`` (default) / ``"bf16"`` / ``"int8_ef"`` / a
    policy instance — the reduce-scatter runs under the policy in WIRE
    mode, so int8 really moves int8 on the grad hop.

    ``replicated_args``: positional indices into ``*batch`` that are NOT
    batch-sharded (an RNG key, a step index) and ride replicated instead.
    """
    policy = resolve_policy(grad_comm)
    _reject_unsupported(optimizer)
    extra = [a for a in mesh.axis_names if a != axis and mesh.shape[a] > 1]
    if extra:
        raise NotImplementedError(
            f"update sharding is the PLAIN data-parallel regime "
            f"(arXiv:2004.13336): mesh has non-trivial axes {extra} beyond "
            f"{axis!r} — use make_zero_train_step / the GSPMD builders for "
            f"hybrid meshes")
    replicated_args = tuple(sorted(set(int(i) for i in replicated_args)))
    R = mesh.shape[axis]
    n = _tree_size(params0)
    # one padding formula for every entry point: stateless policies pad to
    # a multiple of R, int8 to block*R (matching policy.residual_for)
    multiple = int(getattr(policy, "block", 1)) * max(R, 1)
    n_pad = -(-n // multiple) * multiple
    shard_len = n_pad // R

    flat0, meta0 = _flatten_tree(params0, multiple, total=n_pad)
    # optimizer state over the fused flat vector: slots are (n_pad,) and
    # shard 1/R over `axis`; value-dependent inits (e.g. accumulators
    # seeded from the param) see the exact padded param vector
    opt0 = optimizer.init_state({"flat": flat0})
    state0 = {"params": params0, "opt": opt0}
    if policy.stateful:
        e0 = policy.residual_for(params0, axis_size=R)
        # per-replica stacked residual (localsgd's layout): each replica
        # carries its OWN full-length accumulated quantization error
        state0["comm_e"] = jnp.zeros((R,) + e0.shape, e0.dtype)

    state_specs = update_sharding_rules(axis).resolve(state0)
    state0 = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        state0, state_specs)

    def body(state, lr, *batch):
        # inside shard_map: params replicated, opt slot leaves are this
        # replica's (shard_len,) slice, batch rows are this replica's share
        params = state["params"]
        loss, grads = jax.value_and_grad(loss_of)(params, *batch)

        e = state["comm_e"][0] if policy.stateful else None
        # the paper's first half: each replica receives the 1/R shard of
        # the cross-replica MEAN gradient (int8: the one wire hop here is
        # the quantized all_to_all)
        g_shard, meta, new_e = policy.reduce_scatter(grads, axis, e)

        # this replica's current param shard, sliced from the replicated
        # tree (no second authority copy: params stay 1× replicated)
        flat_p, _ = _flatten_tree(params, multiple, total=n_pad)
        p_shard = lax.dynamic_slice_in_dim(
            flat_p, lax.axis_index(axis) * shard_len, shard_len)

        # the update touches 1/R of the state — the ~R× FLOP/HBM saving
        new_sh, new_opt = optimizer.update(
            {"flat": g_shard}, state["opt"], {"flat": p_shard}, lr=lr)

        # the paper's second half: all-gather the updated shards back into
        # the replicated param tree (same bytes the all-reduce second half
        # would have moved)
        flat_new = lax.all_gather(new_sh["flat"], axis, tiled=True)
        new_params = _unflatten_tree(flat_new, meta)

        out = {"params": new_params, "opt": new_opt}
        if policy.stateful:
            out["comm_e"] = new_e[None]
        return out, lax.pmean(loss, axis)

    batch_spec = make_spec(axis)

    # shard_map specs are positional; rebuild per-call for variadic batches
    @functools.lru_cache(maxsize=8)
    def _compiled(n_batch):
        b_specs = tuple(replicated_spec() if i in replicated_args
                        else batch_spec for i in range(n_batch))
        w = _shard_map(
            body, mesh=mesh,
            in_specs=(state_specs, replicated_spec()) + b_specs,
            out_specs=(state_specs, replicated_spec()),
            # check_vma off: the updated params are rebuilt from an
            # all_gather of per-replica shards — value-identical on every
            # replica, but not statically provable through the
            # dynamic-slice/update/gather round trip (dgc.py's rationale)
            check_vma=False)
        return jax.jit(w, donate_argnums=(0,) if donate else ())

    def step(state, lr, *batch):
        return _compiled(len(batch))(state, jnp.asarray(lr, jnp.float32),
                                     *batch)

    from ..telemetry import instrument_train_step
    from ..telemetry_memory import current_memory_ledger
    _ml = current_memory_ledger()
    if _ml is not None:
        # allocation-site registration: the sharded flat slots land in
        # the `optimizer_state` pool as 1/R addressable shards, so a
        # census MEASURES the paper's ~R× HBM saving (bench pins it)
        _ml.register_train_state(state0, name="dp_update_sharded")
    return instrument_train_step(step, monitor, "dp_update_sharded",
                                 comm=comm_info(params0, policy)), state0
