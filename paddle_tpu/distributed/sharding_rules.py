"""Unified sharding-rules layer — THE place array-layout decisions live.

Before this module, zero.py, localsgd.py, dgc.py, spmd.py,
pipeline_engine.py and the GPT builders each hand-threaded their own
``PartitionSpec`` literals (65 sites across 12 files), and nothing tied a
layout decision to the executables compiled under it.  This module owns
all of it:

1. **Spec constructors** (:func:`make_spec`, :func:`replicated_spec`,
   :func:`replica_stacked_spec`, :func:`batch_spec`, ...): the ONLY
   sanctioned ``PartitionSpec`` construction sites.  The tpulint rule
   ``raw-partition-spec`` machine-enforces that no other module builds a
   literal spec, so a layout change is a one-file diff here.

2. **Metadata-driven inference** (:func:`build_param_specs`,
   :func:`build_state_shardings`): the TP/PP/ZeRO spec inference that
   previously lived in ``spmd.py`` — params carry ``_dims_mapping`` /
   ``_pipe_stacked`` annotations, optimizer slots follow their params and
   pick up the "sharding" axis for ZeRO stages.  Moved verbatim so every
   trainer lowers identically to before the move (parity pinned by
   tests/test_sharding_rules.py).

3. **Rules-based resolver** (:class:`ShardingRules`): ordered
   ``(regex, PartitionSpec)`` rules matched against ``/``-joined tree
   paths (the ``match_partition_rules`` shape proven by the JAX LLM
   training community) — scalar/size-1 leaves are exempt (always
   replicated), unmatched paths follow an explicit policy (``"raise"`` |
   ``"replicate"``), axes that do not divide a dimension follow an
   explicit ``indivisible`` policy with byte-accounted fallback.  Covers
   params, optimizer-state trees (:meth:`ShardingRules.resolve_state`)
   and KV-cache pools (plain trees — :meth:`ShardingRules.resolve`).

4. **Stable digests** (:meth:`ShardingRules.digest`,
   :func:`spec_tree_digest`, :func:`sharding_rules_digest`): content
   digests of rule sets and resolved spec trees.  ``jit/aot.py`` folds
   :func:`sharding_rules_digest` into its environment fingerprint and
   validates it per cache entry, so editing a rule here can never revive
   a stale-spec executable from disk.

5. **Replication-fallback accounting** (:func:`replication_fallback`,
   :func:`resolve_flat_shard_spec`): any spot that quietly falls back to
   full replication (a non-divisible flat residual, an unmatched path
   under ``unmatched="replicate"``) now warns AND bumps
   ``sharding_replicated_fallback_bytes`` /
   ``sharding_replicated_fallback_leaves`` so the replicated bytes are
   visible in the stats registry, never silent.

The automatic cross-replica weight-update sharding for plain
data-parallel training (arXiv:2004.13336) that consumes this resolver
lives in :mod:`paddle_tpu.distributed.update_sharding`.
"""

from __future__ import annotations

import hashlib
import re
import warnings
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "CATALOG_VERSION", "ShardingRules", "activation_batch_spec",
    "batch_spec", "build_param_specs", "build_state_shardings",
    "make_spec", "match_partition_rules", "override_leading_axis",
    "register_rules", "replica_stacked_spec", "replicated_spec",
    "replication_fallback", "resolve_flat_shard_spec",
    "sep_activation_spec", "sharding_rules_digest", "spec_tree_digest",
    "unregister_rules",
]

#: Bump when the SEMANTICS of the built-in inference below change without
#: the code path changing shape — the catalog digest folds it in, so every
#: AOT-cached executable compiled under the old semantics is invalidated.
CATALOG_VERSION = 1

#: The built-in rule catalog: one row per layout decision this module
#: makes.  ``sharding_rules_digest()`` digests these rows, so editing a
#: rule (or its semantics, via CATALOG_VERSION) changes the digest that
#: jit/aot.py bakes into cache-entry environments.
_RULE_CATALOG: Tuple[Tuple[str, str], ...] = (
    ("tp", "params with _dims_mapping={dim: axis} shard that dim on the "
           "axis when the axis exists, has size>1, and divides the dim"),
    ("pp", "_pipe_stacked params shard dim 0 over 'pipe' when divisible"),
    ("zero3", "zero_stage>=3 shards the first free divisible param dim "
              "over 'sharding'"),
    ("slots", "optimizer slots follow their param's spec; zero_stage>=1 "
              "adds 'sharding' on the first free divisible dim"),
    ("scalars", "scalar/size-1 leaves are always replicated"),
    ("dp_update", "plain-DP weight-update sharding: flat optimizer shards "
                  "carry a leading replica dim over the dp axis "
                  "(update_sharding.py)"),
    ("flat_residual", "flat comm residuals ride an axis only when the "
                      "length divides; otherwise replicate WITH byte "
                      "accounting (resolve_flat_shard_spec)"),
)

#: Explicitly registered custom rule sets (name -> digest); folded into
#: ``sharding_rules_digest()``.  Registration is process-global state —
#: register only rule sets that genuinely govern AOT-compiled programs in
#: this process, and keep the set identical across processes sharing an
#: executable cache (docs/SHARDING.md).
_REGISTERED: Dict[str, str] = {}


# --------------------------------------------------------------------------
# spec constructors — the only sanctioned PartitionSpec literals
# --------------------------------------------------------------------------

def make_spec(*entries) -> PartitionSpec:
    """``PartitionSpec(*entries)`` — the constructor every other module
    uses instead of a raw literal (enforced by tpulint raw-partition-spec)."""
    return PartitionSpec(*entries)


def replicated_spec() -> PartitionSpec:
    """Fully replicated layout (``PartitionSpec()``)."""
    return PartitionSpec()


def replica_stacked_spec(leaf, axis: str) -> PartitionSpec:
    """Leading-dim-over-``axis`` layout for per-replica stacked state
    (localsgd params/opt, dgc residuals): ``P(axis, None, ..., None)``
    padded to the leaf's rank."""
    return PartitionSpec(axis, *([None] * (np.ndim(leaf) - 1)))


def batch_spec(mesh: Mesh, axis: str = "data") -> PartitionSpec:
    """Batch-dim layout: ``P(axis)`` when the axis exists with size > 1 on
    ``mesh``, else replicated (single-replica CPU fallback)."""
    if axis in mesh.axis_names and mesh.shape[axis] > 1:
        return PartitionSpec(axis)
    return PartitionSpec()


def activation_batch_spec(mesh: Mesh) -> Optional[PartitionSpec]:
    """(B, L, H) activation layout for the GPT builders: batch on "data",
    sequence on "sep" when sequence parallelism is on; None when the mesh
    gives no reason to constrain (single data replica, no sep)."""
    if "sep" in mesh.shape and mesh.shape["sep"] > 1:
        return PartitionSpec("data", "sep", None)
    if "data" in mesh.shape and mesh.shape["data"] > 1:
        return PartitionSpec("data", None, None)
    return None


def sep_activation_spec(ndim: int = 4, axis: str = "sep",
                        seq_dim: int = 1) -> PartitionSpec:
    """Sequence-parallel shard_map operand layout: ``axis`` on the
    sequence dim, everything else replicated (the ring/Ulysses attention
    in/out spec: ``P(None, "sep", None, None)`` at the default rank)."""
    entries: list = [None] * ndim
    entries[seq_dim] = axis
    return PartitionSpec(*entries)


def override_leading_axis(spec: PartitionSpec, ndim: int,
                          axis: str) -> PartitionSpec:
    """``spec`` widened to ``ndim`` entries with dim 0 forced onto
    ``axis`` — the pipeline engine's stacked-parameter layout (leading
    layer dim over "pipe")."""
    entries = [None] * ndim
    for i, a in enumerate(spec):
        entries[i] = a
    entries[0] = axis
    return PartitionSpec(*entries)


# --------------------------------------------------------------------------
# replication-fallback accounting
# --------------------------------------------------------------------------

def replication_fallback(kind: str, name: str, nbytes: int, *,
                         axis: Optional[str] = None,
                         degree: Optional[int] = None,
                         tracer=None) -> None:
    """Record one quietly-replicated tensor: warn, bump the stats
    registry, and (when a telemetry tracer is supplied) emit a structured
    ``sharding_fallback`` event.  Every path that degrades a sharded
    layout to full replication routes through here so the replicated
    bytes are observable (OBSERVABILITY.md)."""
    from ..utils.stats import stat_add
    stat_add("sharding_replicated_fallback_bytes", int(nbytes))
    stat_add("sharding_replicated_fallback_leaves", 1)
    detail = f" over axis {axis!r} (degree {degree})" if axis else ""
    warnings.warn(
        f"sharding: {kind} {name!r} stays fully replicated{detail} — "
        f"{nbytes / 1e6:.2f} MB per device that a divisible layout would "
        f"shard (stat: sharding_replicated_fallback_bytes)")
    if tracer is not None:
        tracer.emit("sharding_fallback", kind=kind, name=name,
                    bytes=int(nbytes), axis=axis, degree=degree)


def resolve_flat_shard_spec(name: str, length: int, mesh: Mesh, axis: str,
                            *, itemsize: int = 4,
                            tracer=None) -> PartitionSpec:
    """Layout for a flat fp32 buffer (grad-comm residuals, fused shards):
    ``P(axis)`` when ``length`` divides over the axis, else replicated
    WITH fallback accounting — the fix for the silent ``P()`` fallback
    that zero.py's comm residual used to take."""
    deg = mesh.shape.get(axis, 1)
    if deg > 1 and length % deg == 0:
        return PartitionSpec(axis)
    if deg > 1:
        replication_fallback("flat-residual", name, length * itemsize,
                             axis=axis, degree=deg, tracer=tracer)
    return PartitionSpec()


# --------------------------------------------------------------------------
# metadata-driven inference (moved verbatim from spmd.py — trainers lower
# identically; spmd.py re-exports these names for compatibility)
# --------------------------------------------------------------------------

def _spec_for_param(name: str, p, mesh: Mesh, named_params: Dict,
                    zero_stage: int, stacked_pipe: bool) -> PartitionSpec:
    ndim = len(p.shape)
    entries = [None] * ndim
    meta = getattr(named_params.get(name), "_dims_mapping", None) \
        if named_params else None
    if meta is None:
        meta = getattr(p, "_dims_mapping", None) or {}
    for dim, axis in meta.items():
        if axis in mesh.axis_names and mesh.shape[axis] > 1 and \
                p.shape[int(dim)] % mesh.shape[axis] == 0:
            entries[int(dim)] = axis
    if stacked_pipe and "pipe" in mesh.axis_names and mesh.shape["pipe"] > 1 \
            and ndim >= 1 and entries[0] is None and \
            p.shape[0] % mesh.shape["pipe"] == 0 and \
            getattr(named_params.get(name), "_pipe_stacked", False):
        entries[0] = "pipe"
    if zero_stage >= 3 and "sharding" in mesh.axis_names and \
            mesh.shape["sharding"] > 1:
        for d in range(ndim):
            if entries[d] is None and p.shape[d] % mesh.shape["sharding"] == 0:
                entries[d] = "sharding"
                break
    return PartitionSpec(*entries)


def build_param_specs(params: Dict[str, Any], mesh: Mesh, layer=None,
                      zero_stage: int = 0) -> Dict[str, PartitionSpec]:
    named = dict(layer.named_parameters()) if layer is not None else {}
    return {name: _spec_for_param(name, p, mesh, named, zero_stage, True)
            for name, p in params.items()}


def _slot_spec(param_spec: PartitionSpec, p, mesh: Mesh,
               zero_stage: int) -> PartitionSpec:
    """Optimizer slots follow param sharding; ZeRO-1/2 additionally shards
    them over "sharding" (reference DygraphShardingOptimizer /
    ShardingOptimizerStage2 semantics, without the manual bucketing)."""
    entries = list(param_spec) + [None] * (len(p.shape) - len(param_spec))
    if zero_stage >= 1 and "sharding" in mesh.axis_names and \
            mesh.shape["sharding"] > 1 and "sharding" not in entries:
        for d in range(len(p.shape)):
            if entries[d] is None and p.shape[d] % mesh.shape["sharding"] == 0:
                entries[d] = "sharding"
                break
    return PartitionSpec(*entries)


def build_state_shardings(state, params_specs: Dict[str, PartitionSpec],
                          mesh: Mesh, zero_stage: int, params):
    """Shardings for the full TrainState pytree {params, opt, buffers}."""
    def param_sh(name):
        return NamedSharding(mesh, params_specs[name])

    p_sh = {k: param_sh(k) for k in state["params"]}
    rep = NamedSharding(mesh, replicated_spec())

    def slot_sh(path_name, slots):
        out = {}
        for sname, val in slots.items():
            if hasattr(val, "shape") and len(val.shape) > 0:
                out[sname] = NamedSharding(
                    mesh, _slot_spec(params_specs[path_name],
                                     params[path_name], mesh, zero_stage))
            else:
                out[sname] = rep
        return out

    opt_sh = {"step": rep,
              "slots": {k: slot_sh(k, v)
                        for k, v in state["opt"]["slots"].items()}}
    buf_sh = {k: rep for k in state["buffers"]}
    return {"params": p_sh, "opt": opt_sh, "buffers": buf_sh}


# --------------------------------------------------------------------------
# path utilities + digests
# --------------------------------------------------------------------------

def _path_str(path) -> str:
    """'/'-joined string for a jax key path (DictKey/SequenceKey/...)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _leaf_size(leaf) -> int:
    shape = tuple(getattr(leaf, "shape", ()) or ())
    return int(np.prod(shape)) if shape else 1


def _leaf_nbytes(leaf) -> int:
    dt = getattr(leaf, "dtype", None)
    itemsize = np.dtype(dt).itemsize if dt is not None else 4
    return _leaf_size(leaf) * itemsize


def _canon_spec(spec) -> Tuple:
    """Canonical hashable form of one spec entry tree leaf."""
    if spec is None:
        return ("<none>",)
    return tuple(tuple(e) if isinstance(e, (tuple, list)) else e
                 for e in spec)


def _is_spec_leaf(x) -> bool:
    return x is None or isinstance(x, PartitionSpec)


def spec_tree_digest(spec_tree) -> str:
    """Stable hex digest of a resolved spec tree: sorted (path, entries)
    pairs under blake2b.  Pass the output of :meth:`ShardingRules.resolve`
    or :func:`build_param_specs`; fold into AOT cache keys when a layout
    decision should invalidate a cached executable."""
    flat = jax.tree_util.tree_flatten_with_path(
        spec_tree, is_leaf=_is_spec_leaf)[0]
    rows = sorted((_path_str(path), _canon_spec(spec)) for path, spec in flat)
    h = hashlib.blake2b(digest_size=16)
    for path, entries in rows:
        h.update(path.encode())
        h.update(repr(entries).encode())
        h.update(b"\x00")
    return h.hexdigest()


def sharding_rules_digest() -> str:
    """Digest of the ACTIVE sharding rules in this process: the built-in
    catalog (CATALOG_VERSION + _RULE_CATALOG) plus every explicitly
    registered :class:`ShardingRules` set.  jit/aot.py folds this into
    ``fingerprint()`` environments and validates it per executable-cache
    entry, so an edit to any rule refuses stale disk executables."""
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((CATALOG_VERSION,) + _RULE_CATALOG).encode())
    for name in sorted(_REGISTERED):
        h.update(name.encode())
        h.update(_REGISTERED[name].encode())
        h.update(b"\x00")
    return h.hexdigest()


def register_rules(rules: "ShardingRules") -> None:
    """Enroll a custom rule set in the process-global active digest (see
    :func:`sharding_rules_digest`).  Call this for rule sets that govern
    programs going through the AOT executable cache; keep the registered
    set identical across processes that share a cache directory."""
    _REGISTERED[rules.name] = rules.digest()


def unregister_rules(name: str) -> None:
    _REGISTERED.pop(name, None)


# --------------------------------------------------------------------------
# the rules-based resolver
# --------------------------------------------------------------------------

class ShardingRules:
    """Ordered (regex, PartitionSpec) sharding rules over tree paths.

    ``rules``: sequence of ``(pattern, spec)`` where ``pattern`` is a
    regex matched with ``re.search`` against the ``/``-joined path of
    each leaf (first match wins — order the specific before the general)
    and ``spec`` is a ``PartitionSpec``, a tuple of entries, or ``None``
    (replicated).

    ``unmatched``: ``"raise"`` (default — an unmatched non-scalar leaf is
    a configuration error) or ``"replicate"`` (fall back to ``P()`` WITH
    replication-fallback accounting).

    ``indivisible``: when a ``mesh`` is bound and a matched axis does not
    divide the leaf's dimension: ``"replicate"`` (default — drop the
    entry, account the bytes) or ``"raise"``.

    Scalar and size-1 leaves are always replicated, whatever the rules
    say — a scalar cannot be usefully sharded and exempting it keeps rule
    tables free of step-counter noise.
    """

    def __init__(self, rules: Sequence[Tuple[str, Any]], *,
                 unmatched: str = "raise", indivisible: str = "replicate",
                 mesh: Optional[Mesh] = None, name: str = "custom",
                 tracer=None):
        if unmatched not in ("raise", "replicate"):
            raise ValueError(
                f"unmatched must be 'raise' or 'replicate', got {unmatched!r}")
        if indivisible not in ("raise", "replicate"):
            raise ValueError(f"indivisible must be 'raise' or 'replicate', "
                             f"got {indivisible!r}")
        self.rules: Tuple[Tuple[str, PartitionSpec], ...] = tuple(
            (str(pat), self._as_spec(spec)) for pat, spec in rules)
        self._compiled = tuple((re.compile(pat), spec)
                               for pat, spec in self.rules)
        self.unmatched = unmatched
        self.indivisible = indivisible
        self.mesh = mesh
        self.name = str(name)
        self.tracer = tracer

    @staticmethod
    def _as_spec(spec) -> PartitionSpec:
        if spec is None:
            return PartitionSpec()
        if isinstance(spec, PartitionSpec):
            return spec
        if isinstance(spec, (tuple, list)):
            return PartitionSpec(*spec)
        raise TypeError(f"rule spec must be a PartitionSpec, entry tuple, "
                        f"or None; got {type(spec).__name__}")

    # ------------------------------------------------------------ resolve --

    def spec_for(self, path: str, leaf=None) -> PartitionSpec:
        """The spec for one '/'-joined path (scalar exemption + first-match
        + divisibility applied when ``leaf`` is given)."""
        if leaf is not None and _leaf_size(leaf) <= 1:
            return PartitionSpec()
        for rx, spec in self._compiled:
            if rx.search(path):
                return self._fit(path, leaf, spec)
        if self.unmatched == "raise":
            raise ValueError(
                f"sharding rules {self.name!r}: no rule matches path "
                f"{path!r} — add a rule or construct with "
                f"unmatched='replicate'")
        if leaf is not None:
            replication_fallback("unmatched-path", path, _leaf_nbytes(leaf),
                                 tracer=self.tracer)
        return PartitionSpec()

    def _fit(self, path: str, leaf, spec: PartitionSpec) -> PartitionSpec:
        """Trim/pad ``spec`` to the leaf's rank and enforce divisibility
        against the bound mesh (per the ``indivisible`` policy)."""
        if leaf is None:
            return spec
        shape = tuple(getattr(leaf, "shape", ()) or ())
        entries = list(spec)[:len(shape)] + \
            [None] * max(0, len(shape) - len(spec))
        if self.mesh is None:
            return self._squeeze(entries)
        for d, entry in enumerate(entries):
            axes = entry if isinstance(entry, (tuple, list)) else \
                ((entry,) if entry is not None else ())
            deg = 1
            for a in axes:
                deg *= self.mesh.shape.get(a, 1)
            if deg > 1 and shape[d] % deg != 0:
                if self.indivisible == "raise":
                    raise ValueError(
                        f"sharding rules {self.name!r}: axis {entry!r} "
                        f"(degree {deg}) does not divide dim {d} "
                        f"(size {shape[d]}) of {path!r}")
                replication_fallback(
                    "indivisible-dim", f"{path}[{d}]",
                    _leaf_nbytes(leaf), axis=str(entry), degree=deg,
                    tracer=self.tracer)
                entries[d] = None
        return self._squeeze(entries)

    @staticmethod
    def _squeeze(entries) -> PartitionSpec:
        """Drop trailing Nones so rank-fitting never changes spec equality
        (``P(None, None)`` and ``P()`` lower identically; keeping the short
        canonical form makes parity pins and digests rank-independent)."""
        while entries and entries[-1] is None:
            entries = entries[:-1]
        return PartitionSpec(*entries)

    def resolve(self, tree) -> Any:
        """Spec tree (same structure as ``tree``) for any pytree — params,
        KV-cache pools, whole train states.  Paths are '/'-joined."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        specs = [self.spec_for(_path_str(path), leaf) for path, leaf in flat]
        return jax.tree_util.tree_unflatten(treedef, specs)

    def resolve_state(self, state) -> Any:
        """Specs for an optimizer-carrying train state ``{"params": ...,
        "opt": {"step", "slots": {param: {slot: leaf}}}, ...}``: params
        resolve under their own path; each optimizer slot resolves under
        its PARAM's path (slots inherit their param's layout; scalar
        exemption still applies), so one rule table covers both."""
        out = {}
        for key, sub in state.items():
            if key == "opt" and isinstance(sub, dict) and "slots" in sub:
                slot_specs = {}
                for pname, slots in sub["slots"].items():
                    slot_specs[pname] = {
                        sname: self.spec_for(f"params/{pname}", leaf=sval)
                        for sname, sval in slots.items()}
                out["opt"] = {"step": PartitionSpec(), "slots": slot_specs}
                if "step" not in sub:
                    del out["opt"]["step"]
            else:
                prefixed = jax.tree_util.tree_flatten_with_path(sub)
                flat, treedef = prefixed
                specs = [self.spec_for(f"{key}/{_path_str(p)}" if p else key,
                                       leaf) for p, leaf in flat]
                out[key] = jax.tree_util.tree_unflatten(treedef, specs)
        return out

    def shardings(self, tree, mesh: Optional[Mesh] = None) -> Any:
        """``NamedSharding`` tree over ``mesh`` (or the bound mesh)."""
        m = mesh if mesh is not None else self.mesh
        if m is None:
            raise ValueError("shardings() needs a mesh (bind one at "
                             "construction or pass mesh=)")
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(m, s), self.resolve(tree),
            is_leaf=_is_spec_leaf)

    # ------------------------------------------------------------- digest --

    def digest(self) -> str:
        """Stable digest of the rule CONTENT (patterns, specs, policies —
        not the name): two rule sets that resolve identically digest
        identically across processes."""
        h = hashlib.blake2b(digest_size=16)
        h.update(repr((self.unmatched, self.indivisible)).encode())
        for pat, spec in self.rules:
            h.update(pat.encode())
            h.update(repr(_canon_spec(spec)).encode())
            h.update(b"\x00")
        return h.hexdigest()

    def __repr__(self):
        return (f"ShardingRules({self.name!r}, {len(self.rules)} rules, "
                f"unmatched={self.unmatched!r}, digest={self.digest()[:8]})")


def match_partition_rules(rules: Sequence[Tuple[str, Any]], tree,
                          unmatched: str = "raise",
                          mesh: Optional[Mesh] = None) -> Any:
    """Functional shorthand: resolve ``tree`` under ``rules`` in one call
    (the community ``match_partition_rules`` signature)."""
    return ShardingRules(rules, unmatched=unmatched, mesh=mesh,
                         name="match_partition_rules").resolve(tree)
