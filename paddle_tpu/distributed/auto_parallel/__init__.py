"""Auto-parallel (semi-automatic SPMD) annotation API.

Reference: python/paddle/distributed/auto_parallel/ (SURVEY.md §2.4) —
``ProcessMesh`` (process_mesh.py), ``shard_tensor``/``shard_op``
(interface.py), per-tensor DistributedAttribute {process_mesh, dims_mapping}
(dist_attribute.py), plus a 9.6K-LoC propagation/partition/reshard engine
(completion.py:429, partitioner.py:39, reshard.py).

TPU-native: the user-facing annotation API is kept; the entire propagation
engine is deleted — ``dims_mapping`` lowers directly to a
``jax.sharding.NamedSharding`` and **GSPMD propagation** (XLA's sharding
completion) does what completion.py/partitioner.py/reshard.py did, at
compile time, provably consistently.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "get_mesh", "set_mesh"]

_current_mesh: Optional["ProcessMesh"] = None


class ProcessMesh:
    """N-D logical process topology (reference process_mesh.py; IR twin
    ProcessMeshDesc framework.proto:41).  Wraps a jax Mesh."""

    def __init__(self, mesh: Union[Sequence, np.ndarray],
                 dim_names: Optional[List[str]] = None, process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.ndim = arr.ndim
        self.dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(arr.ndim)]
        self.process_ids = arr.reshape(-1).tolist()
        from ...core.device import local_devices
        devs = local_devices()
        if len(devs) < arr.size:
            raise ValueError(f"ProcessMesh needs {arr.size} devices, "
                             f"have {len(devs)}")
        dev_arr = np.array([devs[int(i)] for i in arr.reshape(-1)]).reshape(arr.shape)
        self._jax_mesh = Mesh(dev_arr, tuple(self.dim_names))

    @property
    def mesh(self):
        return self._jax_mesh

    def __enter__(self):
        global _current_mesh
        self._prev = _current_mesh
        _current_mesh = self
        return self

    def __exit__(self, *exc):
        global _current_mesh
        _current_mesh = self._prev
        return False

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            self.shape == other.shape and self.process_ids == other.process_ids

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names}, "
                f"process_ids={self.process_ids})")


def get_mesh() -> Optional[ProcessMesh]:
    return _current_mesh


def set_mesh(mesh: ProcessMesh):
    global _current_mesh
    _current_mesh = mesh


def _spec_from_dims_mapping(ndim: int, dims_mapping, mesh: ProcessMesh) -> P:
    """dims_mapping: list of mesh-dim index per tensor dim (-1 = replicate) —
    the reference's dist_attribute encoding — or a list of dim *names*."""
    entries = []
    for d in range(ndim):
        m = dims_mapping[d] if d < len(dims_mapping) else -1
        if m is None or m == -1:
            entries.append(None)
        elif isinstance(m, str):
            entries.append(m)
        else:
            entries.append(mesh.dim_names[int(m)])
    return P(*entries)


def shard_tensor(x, process_mesh: Optional[ProcessMesh] = None,
                 dims_mapping: Optional[Sequence] = None, **kw):
    """Place/annotate a tensor with a mesh sharding (reference interface.py
    ``shard_tensor``).  Eager: device_put with NamedSharding.  Traced (inside
    jit): with_sharding_constraint — GSPMD propagates from there."""
    pm = process_mesh or _current_mesh
    if pm is None:
        raise ValueError("no ProcessMesh: pass process_mesh= or use "
                         "`with ProcessMesh(...)`")
    raw = getattr(x, "_data", x)
    spec = _spec_from_dims_mapping(getattr(raw, "ndim", len(raw.shape)),
                                   list(dims_mapping or []), pm)
    sh = NamedSharding(pm.mesh, spec)
    if isinstance(raw, jax.core.Tracer):
        out = jax.lax.with_sharding_constraint(raw, sh)
    else:
        out = jax.device_put(raw, sh)
    if isinstance(x, Tensor):
        t = Tensor(out)
        t.stop_gradient = x.stop_gradient
        return t
    return out


def shard_op(op_fn, process_mesh: Optional[ProcessMesh] = None,
             in_dims_mappings: Optional[List] = None,
             out_dims_mappings: Optional[List] = None):
    """Annotate an op's inputs/outputs with shardings (reference
    interface.py ``shard_op``).  Returns a wrapped callable; GSPMD decides
    everything not annotated."""
    pm = process_mesh or _current_mesh

    def wrapped(*args, **kwargs):
        mesh = pm or _current_mesh
        if mesh is None:
            return op_fn(*args, **kwargs)
        a = list(args)
        if in_dims_mappings:
            for i, dm in enumerate(in_dims_mappings):
                if dm is not None and i < len(a):
                    a[i] = shard_tensor(a[i], mesh, dm)
        out = op_fn(*a, **kwargs)
        if out_dims_mappings:
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for i, dm in enumerate(out_dims_mappings):
                if dm is not None and i < len(outs):
                    outs[i] = shard_tensor(outs[i], mesh, dm)
            if isinstance(out, tuple) and hasattr(out, "_fields"):
                out = type(out)(*outs)  # namedtuple ctor takes *fields
            elif isinstance(out, (tuple, list)):
                out = type(out)(outs)
            else:
                out = outs[0]
        return out

    return wrapped
