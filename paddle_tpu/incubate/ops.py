"""Incubate tensor ops (reference: python/paddle/incubate/__init__.py —
segment_{sum,mean,max,min} (incubate/tensor/math.py), graph_send_recv
(incubate/operators/), softmax_mask_fuse / softmax_mask_fuse_upper_triangle
(fused_softmax_mask ops).

TPU-native: segment reductions are ``jax.ops.segment_*`` (native scatter
HLO); graph message passing is gather + segment-reduce; the fused-softmax
ops are plain fp32 compositions — XLA fuses the mask add into the softmax,
which is the entire content of the reference's CUDA kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import apply

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "graph_send_recv", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle"]


def _num_segments(ids, op_name):
    if isinstance(ids, jax.core.Tracer):
        raise ValueError(
            f"{op_name} needs concrete segment ids under jit; pad ids to a "
            f"static num_segments and call the jax.ops primitive directly")
    return int(jax.device_get(jnp.max(ids))) + 1


def _segment_reduce(op_name, x, ids, n):
    """Shared reduction core: zero untouched segments like the reference
    segment_pool kernel (jax fills them with ±inf identities for max/min)."""
    if op_name == "mean":
        s = jax.ops.segment_sum(x, ids, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), ids,
                                num_segments=n)
        return s / jnp.maximum(c, 1.0).reshape((-1,) + (1,) * (x.ndim - 1))
    fn = getattr(jax.ops, f"segment_{op_name}")
    out = fn(x, ids, num_segments=n)
    if op_name in ("max", "min"):
        touched = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), ids,
                                      num_segments=n)
        out = jnp.where((touched > 0).reshape((-1,) + (1,) * (x.ndim - 1)),
                        out, 0)
    return out


def _segment(op_name, data, segment_ids):
    def f(x, ids):
        ids = ids.astype(jnp.int32)
        n = _num_segments(ids, f"segment_{op_name}")
        return _segment_reduce(op_name, x, ids, n)
    return apply(f, data, segment_ids)


def segment_sum(data, segment_ids, name=None):
    return _segment("sum", data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    return _segment("mean", data, segment_ids)


def segment_max(data, segment_ids, name=None):
    return _segment("max", data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _segment("min", data, segment_ids)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """GNN message passing (reference incubate/operators/graph_send_recv):
    gather rows at ``src_index``, reduce them at ``dst_index``."""
    pool = pool_type.lower()
    if pool not in ("sum", "mean", "max", "min"):
        raise ValueError(f"pool_type must be sum/mean/max/min, got {pool_type}")

    def f(xv, src, dst):
        src = src.astype(jnp.int32)
        dst = dst.astype(jnp.int32)
        n = int(out_size) if out_size else xv.shape[0]
        return _segment_reduce(pool, xv[src], dst, n)

    return apply(f, x, src_index, dst_index)


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in fp32 (reference fused_softmax_mask_op.cu —
    the fusion is XLA's job here)."""
    def f(a, m):
        return jax.nn.softmax(a.astype(jnp.float32) + m.astype(jnp.float32),
                              axis=-1).astype(a.dtype)
    return apply(f, x, mask)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal softmax: mask out the upper triangle (reference
    fused_softmax_mask_upper_triangle_op.cu)."""
    def f(a):
        L, M = a.shape[-2], a.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (L, M), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (L, M), 1)
        allowed = col <= row
        z = jnp.where(allowed, a.astype(jnp.float32), -1e30)
        return jax.nn.softmax(z, axis=-1).astype(a.dtype)
    return apply(f, x)
