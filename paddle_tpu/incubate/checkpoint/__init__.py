from . import auto_checkpoint  # noqa: F401
from .auto_checkpoint import train_epoch_range  # noqa: F401

__all__ = ["auto_checkpoint", "train_epoch_range"]
