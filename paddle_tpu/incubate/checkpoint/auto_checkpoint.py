"""Timer-based auto-checkpoint (reference:
python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71).

The reference's TrainEpochRange wraps the epoch loop: it periodically
snapshots registered state to a checkpoint dir (HDFS there, local/NFS here)
and, on restart, resumes the loop from the last saved epoch.  Same contract
here, driven by env vars of the same spirit:

- ``PADDLE_TPU_CHECKPOINT_DIR``  — where snapshots go (required to enable)
- ``PADDLE_TPU_CHECKPOINT_INTERVAL`` — min seconds between saves (default 60)

Usage::

    for epoch in acp.train_epoch_range(max_epoch, save_fn=..., load_fn=...):
        train_one_epoch(...)

``save_fn(path)`` persists user state; ``load_fn(path)`` restores it.  The
epoch counter itself is managed by this module (saved atomically next to the
user state), so a relaunched job continues where it stopped.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Iterator, Optional

__all__ = ["train_epoch_range", "TrainEpochRange"]


class TrainEpochRange:
    def __init__(self, max_epoch_num: int, name: str = "acp",
                 save_fn: Optional[Callable[[str], None]] = None,
                 load_fn: Optional[Callable[[str], None]] = None,
                 checkpoint_dir: Optional[str] = None,
                 save_checkpoint_inter: Optional[float] = None):
        self.max_epoch_num = int(max_epoch_num)
        self.name = name
        self.save_fn = save_fn
        self.load_fn = load_fn
        self.dir = checkpoint_dir or os.environ.get("PADDLE_TPU_CHECKPOINT_DIR")
        self.interval = float(
            save_checkpoint_inter
            if save_checkpoint_inter is not None
            else os.environ.get("PADDLE_TPU_CHECKPOINT_INTERVAL", "60"))
        self._last_save = 0.0
        self.restored_epoch = -1

    # -- paths -------------------------------------------------------------
    def _meta_path(self) -> str:
        return os.path.join(self.dir, f"{self.name}.meta.json")

    def _state_path(self) -> str:
        return os.path.join(self.dir, f"{self.name}.state")

    # -- save/restore ------------------------------------------------------
    def _restore(self):
        if not self.dir or not os.path.exists(self._meta_path()):
            return
        with open(self._meta_path()) as f:
            meta = json.load(f)
        self.restored_epoch = int(meta.get("epoch", -1))
        if self.load_fn is not None and os.path.exists(self._state_path()):
            self.load_fn(self._state_path())

    def _save(self, epoch: int, force: bool = False):
        if not self.dir:
            return
        now = time.time()
        if not force and now - self._last_save < self.interval:
            return
        os.makedirs(self.dir, exist_ok=True)
        if self.save_fn is not None:
            # write state to a tmp path and rename, so a crash mid-save never
            # corrupts the state the committed meta points at
            state_tmp = self._state_path() + ".tmp"
            self.save_fn(state_tmp)
            os.replace(state_tmp, self._state_path())
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch, "ts": now, "name": self.name}, f)
        os.replace(tmp, self._meta_path())  # atomic: meta commits the snapshot
        self._last_save = now

    # -- the range ---------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        self._restore()
        start = self.restored_epoch + 1
        for epoch in range(start, self.max_epoch_num):
            yield epoch
            self._save(epoch, force=(epoch == self.max_epoch_num - 1))


def train_epoch_range(max_epoch_num: int, save_fn=None, load_fn=None,
                      checkpoint_dir=None, save_checkpoint_inter=None,
                      name: str = "acp") -> TrainEpochRange:
    """Resumable epoch range (reference auto_checkpoint._get_train_epoch_range)."""
    return TrainEpochRange(max_epoch_num, name=name, save_fn=save_fn,
                           load_fn=load_fn, checkpoint_dir=checkpoint_dir,
                           save_checkpoint_inter=save_checkpoint_inter)
