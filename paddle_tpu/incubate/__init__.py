"""Incubating APIs (reference: python/paddle/incubate/__init__.py).

Graduated-but-experimental surface: LookAhead / ModelAverage optimizers
(reference incubate/optimizer/) and the auto-checkpoint machinery
(reference incubate/checkpoint/auto_checkpoint.py) live here, mirroring the
reference layout.
"""

from . import asp, checkpoint, nn, optimizer  # noqa: F401
from .checkpoint import auto_checkpoint  # noqa: F401
from .ops import (graph_send_recv, segment_max, segment_mean,  # noqa: F401
                  segment_min, segment_sum, softmax_mask_fuse,
                  softmax_mask_fuse_upper_triangle)
from .optimizer import LookAhead, ModelAverage  # noqa: F401

__all__ = ["optimizer", "checkpoint", "asp", "nn", "LookAhead", "ModelAverage",
           "auto_checkpoint", "segment_sum", "segment_mean", "segment_max",
           "segment_min", "graph_send_recv", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle"]


class LayerHelper:
    """fluid-internal layer builder (reference fluid/layer_helper.py),
    surfaced in incubate for legacy imports; the dynamic Layer system
    replaces it — constructing one points to nn.Layer."""

    def __init__(self, *a, **k):
        raise RuntimeError("LayerHelper builds static-graph ops; subclass "
                          "paddle.nn.Layer instead")


def fuse_resnet_unit_pass(*a, **k):
    """cudnn resnet_unit fusion pass (reference fuse_resnet_unit_pass) —
    XLA performs conv+BN+activation fusion automatically; no-op."""
