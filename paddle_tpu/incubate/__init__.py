"""Incubating APIs (reference: python/paddle/incubate/__init__.py).

Graduated-but-experimental surface: LookAhead / ModelAverage optimizers
(reference incubate/optimizer/) and the auto-checkpoint machinery
(reference incubate/checkpoint/auto_checkpoint.py) live here, mirroring the
reference layout.
"""

from . import asp, checkpoint, optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

__all__ = ["optimizer", "checkpoint", "asp", "LookAhead", "ModelAverage"]
