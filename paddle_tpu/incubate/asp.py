"""ASP — automatic structured (n:m) sparsity
(reference: fluid/contrib/sparsity/asp.py, utils.py — 2:4 mask generation,
prune_model, optimizer decoration that re-masks after every step).

TPU-native note: the reference's payoff is Ampere sparse tensor cores; XLA
has no 2:4 MXU mode, so here ASP is a *training technique* (mask-and-keep
pruning with optimizer re-masking) whose artifact — a model whose weights
are exactly n:m sparse — can be served by any 2:4-capable backend.  Mask
computation is pure jnp (top-n |magnitude| per m-block via one reshape +
top_k), so pruning whole models jit-compiles.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter

__all__ = ["create_mask", "check_sparsity", "prune_model", "decorate",
           "set_excluded_layers", "reset_excluded_layers", "ASPHelper"]


def create_mask(w, n: int = 2, m: int = 4):
    """Boolean keep-mask with the top-``n`` |values| in every ``m`` block
    along the last dim (reference sparsity/utils.py get_mask_1d)."""
    arr = jnp.asarray(getattr(w, "_data", w))
    if arr.shape[-1] % m != 0:
        raise ValueError(f"last dim ({arr.shape[-1]}) must divide by m={m}")
    blocks = arr.reshape(-1, m)
    # threshold = n-th largest |value| per block; ties keep the earlier entry
    mag = jnp.abs(blocks)
    order = jnp.argsort(-mag, axis=-1, stable=True)
    ranks = jnp.argsort(order, axis=-1, stable=True)   # rank of each entry
    mask = (ranks < n).reshape(arr.shape)
    return mask


def check_sparsity(w, n: int = 2, m: int = 4) -> bool:
    """True iff every m-block along the last dim has ≤ n nonzeros."""
    arr = np.asarray(getattr(w, "_data", w))
    if arr.shape[-1] % m != 0:
        return False
    blocks = (arr.reshape(-1, m) != 0).sum(axis=-1)
    return bool((blocks <= n).all())


class ASPHelper:
    """Mask registry + optimizer hook (reference asp.py:245 ASPHelper)."""

    _excluded: List[str] = []
    _masks: Dict[int, jnp.ndarray] = {}

    @classmethod
    def prunable(cls, layer) -> List[Parameter]:
        out = []
        for name, p in layer.named_parameters():
            if any(ex in name for ex in cls._excluded):
                continue
            if len(p.shape) >= 2 and p.shape[-1] % 4 == 0:
                out.append(p)
        return out

    @classmethod
    def prune(cls, layer, n: int, m: int):
        for p in cls.prunable(layer):
            mask = create_mask(p._data, n, m)
            p._data = jnp.where(mask, p._data, 0)
            cls._masks[id(p)] = mask

def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Prune all prunable weights of ``model`` to n:m sparsity in place and
    register their masks for optimizer re-masking (reference asp.py:149)."""
    ASPHelper.prune(model, n, m)
    return model


def decorate(optimizer):
    """Wrap ``optimizer.step`` so updated weights are re-masked after every
    step (reference asp.py:110 OptimizerWithSparsityGuarantee): gradient
    steps may revive pruned entries; the mask zeroes them again."""
    orig_step = optimizer.step

    def step():
        orig_step()
        params = optimizer._parameter_list or []
        for p in params:
            mask = ASPHelper._masks.get(id(p))
            if mask is not None and mask.shape == tuple(p.shape):
                p._data = jnp.where(mask, p._data, 0)

    optimizer.step = step
    optimizer.minimize_step = step
    return optimizer


def set_excluded_layers(main_program=None, param_names: Optional[List[str]] = None):
    """Exclude parameters whose name contains any given substring."""
    if isinstance(main_program, (list, tuple)) and param_names is None:
        param_names = list(main_program)
    ASPHelper._excluded = list(param_names or [])


def reset_excluded_layers(main_program=None):
    ASPHelper._excluded = []
