"""LookAhead optimizer (reference: python/paddle/incubate/optimizer/lookahead.py).

k fast steps with the inner optimizer, then the slow weights move
alpha·(fast − slow) and the fast weights reset to the slow ones
("Lookahead Optimizer: k steps forward, 1 step back", Zhang et al. 2019).

Wraps any paddle_tpu Optimizer; works in both eager mode (``step()``) and
the functional jit path (``init_state``/``update`` — the slow copies ride
in the state pytree so the whole schedule stays inside one compiled step,
with the k-boundary expressed as a ``jnp.where`` instead of host control
flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer


class LookAhead(Optimizer):
    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not isinstance(inner_optimizer, Optimizer):
            raise TypeError("inner optimizer must be a paddle_tpu Optimizer")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha should be in [0, 1], got {alpha}")
        if k < 1:
            raise ValueError(f"k should be >= 1, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow = {}
        self._k_count = 0
        # Optimizer.__init__ is deliberately not called (everything delegates
        # to the inner optimizer); satisfy the attributes that inherited
        # entry points read so none of them AttributeError.
        self._grad_clip = None
        self._weight_decay = None
        self._learning_rate = inner_optimizer._learning_rate
        self._param_groups = None
        self._accum = {}
        self._step_count = 0

    # ---------------------------------------------------------------- eager
    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    @_parameter_list.setter
    def _parameter_list(self, v):  # Optimizer.__init__ not called; ignore
        pass

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, value):
        self.inner_optimizer.set_lr(value)
        self._learning_rate = self.inner_optimizer._learning_rate

    def step(self):
        params = self.inner_optimizer._parameter_list
        if params is None:
            raise ValueError("inner optimizer has no parameter list")
        for p in params:
            if id(p) not in self._slow:
                self._slow[id(p)] = jnp.array(p._data)
        self.inner_optimizer.step()
        self._k_count += 1
        if self._k_count % self.k == 0:
            for p in params:
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (p._data - slow)
                p._data = slow
                self._slow[id(p)] = slow

    minimize_step = step  # re-point the class alias at the override

    def clear_grad(self, set_to_zero: bool = False):
        self.inner_optimizer.clear_grad(set_to_zero)

    def minimize(self, loss, **kwargs):
        loss.backward()
        self.step()
        return None, None

    # ----------------------------------------------------------- functional
    def init_state(self, params):
        return {
            "inner": self.inner_optimizer.init_state(params),
            "slow": jax.tree_util.tree_map(jnp.array, params),
            "k_count": jnp.zeros([], jnp.int32),
        }

    def update(self, grads, state, params, lr=None):
        new_params, inner_state = self.inner_optimizer.update(
            grads, state["inner"], params, lr=lr)
        k_count = state["k_count"] + 1
        sync = (k_count % self.k) == 0

        # two passes instead of one returning (slow, fast) pairs: a pair-typed
        # tree_map result cannot be split again when the params pytree itself
        # contains tuples (XLA CSEs the duplicated merge arithmetic anyway)
        new_slow = jax.tree_util.tree_map(
            lambda s, f: jnp.where(sync, s + self.alpha * (f - s), s),
            state["slow"], new_params)
        out_params = jax.tree_util.tree_map(
            lambda s, f: jnp.where(
                sync, (s + self.alpha * (f - s)).astype(f.dtype), f),
            state["slow"], new_params)
        return out_params, {"inner": inner_state, "slow": new_slow,
                            "k_count": k_count}

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_k_count"] = self._k_count
        return sd

    def set_state_dict(self, state_dict):
        self._k_count = int(state_dict.pop("lookahead_k_count", 0))
        self.inner_optimizer.set_state_dict(state_dict)
