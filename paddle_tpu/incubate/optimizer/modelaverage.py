"""ModelAverage (reference: python/paddle/incubate/optimizer/modelaverage.py,
fluid/optimizer.py:3619).

Maintains the reference's three-accumulator running sum of parameter values
(sum_1 / sum_2 / sum_3 with window restarts controlled by
``average_window_rate`` and min/max window) and swaps the averaged weights
in for evaluation via ``apply()`` / ``restore()``.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict

import jax.numpy as jnp


class ModelAverage:
    def __init__(self, average_window_rate, parameters=None, min_average_window=10000,
                 max_average_window=10000000, name=None):
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._params = list(parameters) if parameters is not None else []
        self._acc: Dict[int, Dict[str, Any]] = {}
        self._backup: Dict[int, Any] = {}

    def _state(self, p):
        st = self._acc.get(id(p))
        if st is None:
            st = {"sum_1": jnp.zeros_like(p._data), "sum_2": jnp.zeros_like(p._data),
                  "sum_3": jnp.zeros_like(p._data), "num_accumulates": 0,
                  "old_num_accumulates": 0, "num_updates": 0}
            self._acc[id(p)] = st
        return st

    def step(self):
        """Accumulate current parameter values (call after optimizer.step())."""
        for p in self._params:
            st = self._state(p)
            st["sum_1"] = st["sum_1"] + p._data
            st["num_accumulates"] += 1
            st["num_updates"] += 1
            # window restart (reference average_accumulates_op.h:94): fold the
            # live sums into sum_3 (discarding the previous old window) and
            # carry the count over single-counted
            if (st["num_accumulates"] >= self.min_w
                    and st["num_accumulates"] >= min(
                        self.max_w, st["num_updates"] * self.rate)):
                st["sum_3"] = st["sum_1"] + st["sum_2"]
                st["sum_2"] = jnp.zeros_like(st["sum_2"])
                st["sum_1"] = jnp.zeros_like(st["sum_1"])
                st["old_num_accumulates"] = st["num_accumulates"]
                st["num_accumulates"] = 0

    minimize_step = step

    def _average(self, p):
        st = self._state(p)
        total = st["num_accumulates"] + st["old_num_accumulates"]
        if total == 0:
            return p._data
        s = st["sum_1"] + st["sum_2"] + st["sum_3"]
        return (s / total).astype(p._data.dtype)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            self._backup[id(p)] = p._data
            p._data = self._average(p)
        try:
            yield self
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup.pop(id(p))
