"""incubate.nn fused layers (reference: incubate/nn/layer/fused_transformer.py
— FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer).

TPU-native: "fused" here means the flash-attention Pallas kernel plus XLA's
automatic elementwise fusion — the layers share weights-and-math semantics
with the reference's fused CUDA ops while the fusion itself is compiled.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...ops.attention import scaled_dot_product_attention

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(nn.Layer):
    """Pre/post-LN attention block with residual (reference
    fused_attention_op semantics: LN → QKV → FMHA → out-proj → dropout →
    residual [→ LN])."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        if embed_dim % num_heads != 0:
            raise ValueError(f"num_heads ({num_heads}) must divide embed_dim "
                             f"({embed_dim})")
        if kdim not in (None, embed_dim) or vdim not in (None, embed_dim):
            raise NotImplementedError(
                "FusedMultiHeadAttention is self-attention (the reference's "
                "fused_attention op has the same restriction); kdim/vdim "
                "must equal embed_dim")
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.qkv = nn.Linear(embed_dim, 3 * embed_dim,
                             weight_attr=qkv_weight_attr,
                             bias_attr=qkv_bias_attr)
        self.out_proj = nn.Linear(embed_dim, embed_dim,
                                  weight_attr=linear_weight_attr,
                                  bias_attr=linear_bias_attr)
        # only the LayerNorm the chosen mode uses (dead params would bloat
        # optimizer state and state_dicts)
        self.ln = nn.LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = nn.Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        if (key is not None and key is not query) or \
                (value is not None and value is not query) or cache is not None:
            raise NotImplementedError(
                "FusedMultiHeadAttention computes self-attention only "
                "(cross-attention key/value and incremental cache are not "
                "fused; use nn.MultiHeadAttention)")
        residual = query
        x = self.ln(query) if self.normalize_before else query
        B, L, _ = x.shape
        qkv = self.qkv(x)
        H, D = self.num_heads, self.embed_dim // self.num_heads
        q, k, v = [t.reshape([B, L, H, D]) for t in qkv.chunk(3, axis=-1)]
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate,
            training=self.training)
        out = self.out_proj(out.reshape([B, L, self.embed_dim]))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(nn.Layer):
    """LN → linear → act → dropout → linear → dropout → residual
    (reference fused_feedforward_op)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.linear1 = nn.Linear(d_model, dim_feedforward,
                                 weight_attr=linear1_weight_attr,
                                 bias_attr=linear1_bias_attr)
        self.linear2 = nn.Linear(dim_feedforward, d_model,
                                 weight_attr=linear2_weight_attr,
                                 bias_attr=linear2_bias_attr)
        self.ln = nn.LayerNorm(d_model, epsilon=epsilon)
        self.drop_act = nn.Dropout(act_dropout_rate if act_dropout_rate
                                   is not None else dropout_rate)
        self.drop_out = nn.Dropout(dropout_rate)

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        x = self.drop_act(getattr(F, self.activation)(self.linear1(x)))
        x = self.drop_out(self.linear2(x))
        out = residual + x
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(nn.Layer):
    """Attention + FFN block built from the two fused sublayers (reference
    FusedTransformerEncoderLayer)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate
            is not None else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
