"""Pallas paged-attention decode kernel: attention over a block pool,
walking each slot's block table IN-KERNEL via scalar prefetch — no dense
gathered view ever materializes (the PagedKV.gather fallback's transient
disappears; PAPERS.md ragged paged attention, reshaped for this engine's
slot/table layout).

One query per slot (the serving engine's decode tick).  Grid is
(slots, table columns); the k/v BlockSpec index maps read the PREFETCHED
table — ``table[s, j]`` selects which physical pool block the next DMA
fetches — and an online-softmax accumulator runs across the column
dimension exactly like ops/attention.py's flash forward.  Per-slot clocks
and left-pad masks ride along as prefetched scalars.

Beyond the reference snapshot (no serving scheduler there; SURVEY §2.3).
Gated like every Pallas kernel here: real Mosaic lowering on TPU via
FLAGS_use_pallas_kernels, ``interpret=True`` for CPU CI.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _paged_decode_kernel(table_ref, t_ref, pad_ref, q_ref, k_ref, v_ref,
                         o_ref, acc_ref, m_ref, l_ref, *, bs, n_cols,
                         scale):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def body():
        q = q_ref[0].astype(jnp.float32) * scale       # (nh, hd)
        k = k_ref[0].astype(jnp.float32)               # (bs, nh, hd)
        v = v_ref[0].astype(jnp.float32)
        # scores (nh, bs): contract hd, batch over heads
        sc = lax.dot_general(q, k, (((1,), (2,)), ((0,), (1,))),
                             preferred_element_type=jnp.float32)
        pos = j * bs + lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        valid = (pos <= t_ref[s]) & (pos >= pad_ref[s])
        sc = jnp.where(valid, sc, _NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
        p = jnp.exp(sc - m_new)                        # (nh, bs)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
        # (nh, hd): contract positions, batch over heads
        acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    # columns past the clock: the clamped index map (see in_specs) makes
    # every skipped step re-map to the slot's LAST in-range block, which
    # Pallas does not re-fetch — pl.when then skips the FLOPs, so the
    # table tail costs neither DMA nor compute
    @pl.when(j * bs <= t_ref[s])
    def _run():
        body()

    @pl.when(j == n_cols - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def paged_decode_attention(q, pool_k, pool_v, table, t, pad_lens=None,
                           *, interpret=False):
    """Single-position attention over table-selected pool blocks.

    q (S, nh, hd); pool_k/pool_v (NB+1, bs, nh, hd); table (S, C) int32
    (inactive rows pre-zeroed to the trash block by the caller); t (S,)
    int32 per-slot clocks (query attends positions <= t); pad_lens (S,)
    int32 left-pad masks (positions < pad masked), or None.

    Returns (S, nh, hd) in q's dtype.  Exactly cached_attention's kq=1
    semantics over a PagedKV — tests pin the parity against the gather
    fallback."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, nh, hd = q.shape
    NB1, bs = pool_k.shape[:2]
    C = table.shape[1]
    if pad_lens is None:
        pad_lens = jnp.zeros((S,), jnp.int32)
    scale = 1.0 / (hd ** 0.5)

    kernel = functools.partial(_paged_decode_kernel, bs=bs, n_cols=C,
                               scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,                   # table, t, pad
        grid=(S, C),
        in_specs=[
            pl.BlockSpec((1, nh, hd), lambda s, j, tb, tt, pp: (s, 0, 0)),
            # column clamped to the slot's clock: steps past it fetch the
            # same block again, which Pallas skips — real DMA savings for
            # short rows in a deep table
            pl.BlockSpec((1, bs, nh, hd),
                         lambda s, j, tb, tt, pp:
                         (tb[s, jnp.minimum(j, tt[s] // bs)], 0, 0, 0)),
            pl.BlockSpec((1, bs, nh, hd),
                         lambda s, j, tb, tt, pp:
                         (tb[s, jnp.minimum(j, tt[s] // bs)], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, hd),
                               lambda s, j, tb, tt, pp: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, hd), jnp.float32),
            pltpu.VMEM((nh, 1), jnp.float32),
            pltpu.VMEM((nh, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, nh, hd), q.dtype),
        interpret=interpret,
    )(table.astype(jnp.int32), jnp.asarray(t, jnp.int32),
      jnp.asarray(pad_lens, jnp.int32), q, pool_k, pool_v)
