"""Fused softmax cross-entropy (reference: operators/math/cross_entropy.cu +
c_softmax_with_cross_entropy_op.cu — the fused softmax+CE the reference uses
for LM heads).

TPU motivation: the naive ``log_softmax → take_along_axis → mean`` chain over
a (B, L, V) logits tensor materializes the full-precision log-probability
tensor (V=50k ⇒ 1.6GB fp32 at GPT-2 bench shapes) and its gradient pass
re-reads it several times — profiled at ~10ms/step of pure HBM traffic on a
v5e.  These kernels keep the logits in their compute dtype (bf16), reduce in
fp32, and reconstruct ``softmax - onehot`` in one fused pass in the backward
instead of saving log-probs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _lse_and_picked(logits, labels):
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return lse, picked.astype(jnp.float32)


@jax.custom_vjp
def softmax_cross_entropy_mean(logits, labels):
    """Mean CE over all leading dims.  logits (..., V) any float dtype;
    labels (...) int.  Returns a float32 scalar."""
    lse, picked = _lse_and_picked(logits, labels)
    return jnp.mean(lse - picked)


def _ce_fwd(logits, labels):
    lse, picked = _lse_and_picked(logits, labels)
    return jnp.mean(lse - picked), (logits, labels, lse)


def _ce_bwd(res, g):
    logits, labels, lse = res
    n = lse.size
    # exp(l - lse) - onehot fused into one pass over the logits; the one-hot
    # lowers to an iota comparison, never a materialized (…, V) table
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    dlogits = ((p - onehot) * (g / n)).astype(logits.dtype)
    return dlogits, None


softmax_cross_entropy_mean.defvjp(_ce_fwd, _ce_bwd)


@jax.custom_vjp
def softmax_cross_entropy_weighted_mean(logits, labels, weights):
    """Weighted-mean CE: sum(w·ce) / max(sum(w), 1) — the MLM contract
    (ignore-index positions get weight 0; ≙ reference's masked
    softmax_with_cross_entropy + divide in bert pretraining heads)."""
    lse, picked = _lse_and_picked(logits, labels)
    w = weights.astype(jnp.float32)
    return jnp.sum((lse - picked) * w) / jnp.maximum(jnp.sum(w), 1.0)


def _cew_fwd(logits, labels, weights):
    lse, picked = _lse_and_picked(logits, labels)
    w = weights.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(w), 1.0)
    loss = jnp.sum((lse - picked) * w) / denom
    return loss, (logits, labels, lse, w, denom)


def _cew_bwd(res, g):
    logits, labels, lse, w, denom = res
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    scale = (g / denom) * w
    dlogits = ((p - onehot) * scale[..., None]).astype(logits.dtype)
    return dlogits, None, None


softmax_cross_entropy_weighted_mean.defvjp(_cew_fwd, _cew_bwd)
