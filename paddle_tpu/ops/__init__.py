"""paddle_tpu.ops — Pallas/TPU fused kernels.

TPU-native replacements for the reference's operators/fused/ corpus
(fused_attention_op.cu, fused_feedforward_op.cu, fused_dropout_helper.h)."""

from .attention import dense_attention, flash_attention, scaled_dot_product_attention  # noqa: F401
from .custom import CustomOp, custom_op, get_op, list_ops, register_op  # noqa: F401
