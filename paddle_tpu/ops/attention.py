"""Attention kernels.

TPU-native replacement for the reference's fused attention stack
(operators/fused/fused_attention_op.cu, fmha_ref.h:57): a Pallas
flash-attention kernel (online-softmax, O(L) memory) with an XLA einsum
fallback.  Layout convention: (batch, seq, heads, head_dim) — BLHD, matching
paddle's MultiHeadAttention internals.

The Pallas path uses a custom VJP whose backward recomputes blockwise
(flash-style) so long sequences never materialize the L×L score matrix.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.flags import flag
from ..core.tensor import Tensor, apply

_NEG_INF = -1e30


def _use_pallas() -> bool:
    return flag("FLAGS_use_pallas_kernels") and jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# Dense XLA path (also the reference implementation for tests)
# ---------------------------------------------------------------------------

def dense_attention(q, k, v, mask=None, causal=False, scale=None, dropout_p=0.0,
                    dropout_key=None):
    """q,k,v: (B, L, H, D) raw arrays. mask: additive, broadcastable to (B,H,Lq,Lk)."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k) * jnp.asarray(scale, q.dtype)
    if causal:
        Lq, Lk = scores.shape[-2], scores.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
        cmask = (col <= row + (Lk - Lq))
        scores = jnp.where(cmask, scores, jnp.asarray(_NEG_INF, scores.dtype))
    if mask is not None:
        scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


# ---------------------------------------------------------------------------
# Pallas flash attention (TPU)
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                      causal, scale, block_q, block_k, seq_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def body():
        q = q_ref[0].astype(jnp.float32) * scale  # (block_q, D)
        k = k_ref[0].astype(jnp.float32)          # (block_k, D)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[:] = m_new
        l_ref[:] = l_new

    if causal:
        # skip fully-masked kv blocks
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _run():
            body()
    else:
        body()

    n_kv = seq_len // block_k

    @pl.when(ki == n_kv - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _flash_attention_pallas(q, k, v, causal, scale, block_q=256, block_k=256,
                            interpret=False):
    """q,k,v: (BH, L, D). Returns (BH, L, D)."""
    from jax.experimental import pallas as pl

    BH, L, D = q.shape
    block_q = min(block_q, L)
    block_k = min(block_k, L)
    grid = (BH, L // block_q, L // block_k)

    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_flash_fwd_kernel, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k, seq_len=L)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, L, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, causal, scale, block):
    return _flash_fwd_impl(q, k, v, causal, scale, block)


def _flash_fwd_impl(q, k, v, causal, scale, block):
    B, L, H, D = q.shape
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    interpret = jax.default_backend() != "tpu"
    out = _flash_attention_pallas(qt, kt, vt, causal, scale, block_q=block,
                                  block_k=block, interpret=interpret)
    return out.reshape(B, H, L, D).transpose(0, 2, 1, 3)


def _flash_fwd_rule(q, k, v, causal, scale, block):
    out = _flash_fwd_impl(q, k, v, causal, scale, block)
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, block, res, g):
    q, k, v = res
    # Blockwise recompute backward via XLA (correct, O(L^2) compute but does
    # not materialize probs in fp32 for long L thanks to XLA fusion).
    def fwd(q_, k_, v_):
        return dense_attention(q_, k_, v_, mask=None, causal=causal, scale=scale)
    _, vjp = jax.vjp(fwd, q, k, v)
    return vjp(g)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal=False, scale=None):
    """Public flash attention on raw arrays, (B,L,H,D)."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    L = q.shape[1]
    # choose the largest block size that tiles L exactly
    block = next((b for b in (512, 256, 128) if L % b == 0), None)
    if _use_pallas() and block is not None and q.shape == k.shape:
        return _flash_attention(q, k, v, causal, scale, block)
    return dense_attention(q, k, v, mask=None, causal=causal, scale=scale)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Tensor-level entry (BLHD), used by nn.MultiHeadAttention / F.sdpa."""
    from ..core import rng
    dropout_key = None
    if dropout_p > 0.0 and training:
        dropout_key = rng.next_key()

    def f(q, k, v, m, dk):
        if m is None and dk is None:
            return flash_attention(q, k, v, causal=is_causal)
        return dense_attention(q, k, v, mask=m, causal=is_causal,
                               dropout_p=dropout_p if dk is not None else 0.0,
                               dropout_key=dk)
    return apply(f, query, key, value, attn_mask,
                 None if dropout_key is None else Tensor(dropout_key))
