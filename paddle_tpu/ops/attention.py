"""Attention kernels.

TPU-native replacement for the reference's fused attention stack
(operators/fused/fused_attention_op.cu, fmha_ref.h:57): a Pallas
flash-attention kernel (online-softmax, O(L) memory) with an XLA einsum
fallback.  Layout convention: (batch, seq, heads, head_dim) — BLHD, matching
paddle's MultiHeadAttention internals.

Forward supports causal masking, an additive key-padding mask (the BERT
(B, 1, 1, L) shape — reference fused_attention_op.cu consumes the same
broadcast mask), and in-kernel attention-probability dropout driven by a
position-based counter RNG (same bits in forward and backward by
construction, like the reference's seeded dropout in
fused_dropout_helper.h).  The backward is a pair of Pallas kernels
(dQ and dK/dV) that recompute probabilities blockwise from the saved
logsumexp — neither pass materializes the (L, L) score matrix.

Caveat (standard for flash attention): every query row must have at least
one unmasked key, else its logsumexp is -inf and gradients NaN.  Causal +
key-padding masks used by the model zoo satisfy this (CLS is never padded).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.flags import flag
from ..core.tensor import Tensor, apply

_NEG_INF = -1e30


def _use_pallas() -> bool:
    return flag("FLAGS_use_pallas_kernels") and jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# Dense XLA path (also the reference implementation for tests)
# ---------------------------------------------------------------------------

def dense_attention(q, k, v, mask=None, causal=False, scale=None, dropout_p=0.0,
                    dropout_key=None):
    """q,k,v: (B, L, H, D) raw arrays. mask: additive, broadcastable to (B,H,Lq,Lk)."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k) * jnp.asarray(scale, q.dtype)
    if causal:
        Lq, Lk = scores.shape[-2], scores.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
        cmask = (col <= row + (Lk - Lq))
        scores = jnp.where(cmask, scores, jnp.asarray(_NEG_INF, scores.dtype))
    if mask is not None:
        scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


# ---------------------------------------------------------------------------
# Portable in-kernel dropout RNG: murmur3-finalizer hash of (seed, bh, row,
# col).  Position-based, so forward and both backward kernels reproduce the
# exact same keep-mask regardless of their block decomposition, and it lowers
# on both Mosaic (TPU) and the interpret path (CPU tests) — pltpu.prng_* has
# no CPU lowering.
# ---------------------------------------------------------------------------

def position_hash_keep(mixed_seed, row0, col0, shape, dropout_p):
    """Shared keep-mask core: murmur3-finalize hash((row, col) ⊕ mixed_seed)
    ≥ p·2³².  ``mixed_seed`` is a uint32 scalar the caller pre-mixes with any
    extra coordinates (head index etc.); both the attention and fused-LN
    kernels use this one pipeline so the RNG cannot diverge between them."""
    rows = jnp.uint32(row0) + lax.broadcasted_iota(jnp.uint32, shape, 0)
    cols = jnp.uint32(col0) + lax.broadcasted_iota(jnp.uint32, shape, 1)
    x = (rows * jnp.uint32(0x9E3779B1)) ^ (cols * jnp.uint32(0x85EBCA77))
    x = x ^ mixed_seed
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    thresh = jnp.uint32(min(int(dropout_p * 4294967296.0), 4294967295))
    return x >= thresh


def _dropout_keep(seed, bh, q0, k0, shape, dropout_p):
    mixed = seed.astype(jnp.uint32) + jnp.uint32(bh) * jnp.uint32(0xC2B2AE3D)
    return position_hash_keep(mixed, q0, k0, shape, dropout_p)


# ---------------------------------------------------------------------------
# Pallas flash attention: forward
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, km_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *, causal, scale, dropout_p,
                      block_q, block_k, n_k):
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def body():
        q = q_ref[0].astype(jnp.float32) * scale  # (block_q, D)
        k = k_ref[0].astype(jnp.float32)          # (block_k, D)
        v = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        s = s + km_ref[0, 0].astype(jnp.float32)[None, :]
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
        if dropout_p > 0.0:
            keep = _dropout_keep(seed_ref[0], bh, qi * block_q, ki * block_k,
                                 p.shape, dropout_p)
            p_v = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        else:
            p_v = p
        acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
            p_v, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[:] = m_new
        l_ref[:] = l_new

    if causal:
        # skip fully-masked kv blocks
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _run():
            body()
    else:
        body()

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_ref[:] + jnp.log(l))[:, 0]


def _flash_fwd_pallas(q, k, v, kmask, seed, causal, scale, dropout_p,
                      block_q, block_k, n_heads, interpret):
    """q,k,v: (BH, L, D); kmask: (B, L) additive. Returns (out, lse)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, L, D = q.shape
    grid = (BH, L // block_q, L // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel, causal=causal, scale=scale, dropout_p=dropout_p,
        block_q=block_q, block_k=block_k, n_k=L // block_k)
    H = n_heads
    # Row-stat operands (kmask, lse) ride a unit sublane dim: Mosaic requires
    # the last-two block dims be (mult-of-8, mult-of-128) or equal the array
    # dims, so (B, L) with block (1, block) is illegal while (B, 1, L) with
    # block (1, 1, block) is fine.
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # seed (1,)
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // H, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, L, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, L), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(seed, q, k, v, kmask.reshape(kmask.shape[0], 1, L))
    return out, lse.reshape(BH, L)


# ---------------------------------------------------------------------------
# Pallas flash attention: backward (blockwise recompute from saved lse)
#
# P  = exp(S - lse)            (true softmax probs, recomputed per block)
# Pd = keep ∘ P / (1-p)        (dropout-applied probs)
# dV = Pd^T dO
# dPd = dO V^T ;  dS = Pd ∘ dPd - P ∘ delta,   delta = rowsum(dO ∘ O)
# dQ = scale · dS K ;  dK = scale · dS^T Q
# ---------------------------------------------------------------------------

def _bwd_block(q, k, v, do, lse, delta, km, keep_args, causal, scale,
               dropout_p, q0, k0):
    """Shared recompute math. q/do: (bq, D); k/v: (bk, D); lse/delta: (bq,).
    Returns (p, pd, ds) all (bq, bk) fp32."""
    s = lax.dot_general(q.astype(jnp.float32) * scale, k.astype(jnp.float32),
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = s + km.astype(jnp.float32)[None, :]
    if causal:
        rows = q0 + lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = k0 + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols <= rows, s, _NEG_INF)
    p = jnp.exp(s - lse[:, None])
    if dropout_p > 0.0:
        seed, bh = keep_args
        keep = _dropout_keep(seed, bh, q0, k0, p.shape, dropout_p)
        pd = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    else:
        pd = p
    dpd = lax.dot_general(do.astype(jnp.float32), v.astype(jnp.float32),
                          (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    ds = pd * dpd - p * delta[:, None]
    return p, pd, ds


def _flash_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, km_ref, dq_ref, acc_ref, *, causal, scale,
                         dropout_p, block_q, block_k, n_k):
    from jax.experimental import pallas as pl

    bh, qi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def body():
        _, _, ds = _bwd_block(
            q_ref[0], k_ref[0], v_ref[0], do_ref[0], lse_ref[0, 0],
            delta_ref[0, 0], km_ref[0, 0], (seed_ref[0], bh), causal, scale,
            dropout_p, qi * block_q, ki * block_k)
        acc_ref[:] += scale * lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _run():
            body()
    else:
        body()

    @pl.when(ki == n_k - 1)
    def _fin():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, km_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                          causal, scale, dropout_p, block_q, block_k, n_q):
    from jax.experimental import pallas as pl

    bh, ki, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def body():
        _, pd, ds = _bwd_block(
            q_ref[0], k_ref[0], v_ref[0], do_ref[0], lse_ref[0, 0],
            delta_ref[0, 0], km_ref[0, 0], (seed_ref[0], bh), causal, scale,
            dropout_p, qi * block_q, ki * block_k)
        dv_acc[:] += lax.dot_general(
            pd, do_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] += scale * lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip q blocks strictly above the diagonal (no row attends this kv)
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _run():
            body()
    else:
        body()

    @pl.when(qi == n_q - 1)
    def _fin():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, kmask, seed, do, lse, delta, causal, scale,
                      dropout_p, block_q, block_k, n_heads, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, L, D = q.shape
    H = n_heads
    common = dict(causal=causal, scale=scale, dropout_p=dropout_p,
                  block_q=block_q, block_k=block_k)
    data_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # seed
    ]
    # unit sublane dim for row stats — see _flash_fwd_pallas
    kmask3 = kmask.reshape(kmask.shape[0], 1, L)
    lse3 = lse.reshape(BH, 1, L)
    delta3 = delta.reshape(BH, 1, L)

    def qspec(im):
        return pl.BlockSpec((1, block_q, D), im)

    def kspec(im):
        return pl.BlockSpec((1, block_k, D), im)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, n_k=L // block_k, **common),
        grid=(BH, L // block_q, L // block_k),
        in_specs=data_specs + [
            qspec(lambda b, i, j: (b, i, 0)),
            kspec(lambda b, i, j: (b, j, 0)),
            kspec(lambda b, i, j: (b, j, 0)),
            qspec(lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // H, 0, j)),
        ],
        out_specs=qspec(lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, L, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(seed, q, k, v, do, lse3, delta3, kmask3)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, n_q=L // block_q, **common),
        grid=(BH, L // block_k, L // block_q),
        in_specs=data_specs + [
            qspec(lambda b, j, i: (b, i, 0)),
            kspec(lambda b, j, i: (b, j, 0)),
            kspec(lambda b, j, i: (b, j, 0)),
            qspec(lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b // H, 0, j)),
        ],
        out_specs=[kspec(lambda b, j, i: (b, j, 0)),
                   kspec(lambda b, j, i: (b, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((BH, L, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, L, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interpret,
    )(seed, q, k, v, do, lse3, delta3, kmask3)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing (BLHD public layout)
# ---------------------------------------------------------------------------

def _to_bh(x):
    B, L, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, L, D)


def _from_bh(x, B, H):
    BH, L, D = x.shape
    return x.reshape(B, H, L, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_attention(q, k, v, kmask, seed, causal, scale, dropout_p, block):
    out, _ = _flash_fwd(q, k, v, kmask, seed, causal, scale, dropout_p, block)
    return out


def _flash_fwd(q, k, v, kmask, seed, causal, scale, dropout_p, block):
    B, L, H, D = q.shape
    interpret = jax.default_backend() != "tpu"
    out, lse = _flash_fwd_pallas(
        _to_bh(q), _to_bh(k), _to_bh(v), kmask, seed, causal, scale,
        dropout_p, block, block, H, interpret)
    return _from_bh(out, B, H), lse


def _flash_fwd_rule(q, k, v, kmask, seed, causal, scale, dropout_p, block):
    out, lse = _flash_fwd(q, k, v, kmask, seed, causal, scale, dropout_p, block)
    return out, (q, k, v, kmask, seed, out, lse)


def _flash_bwd_rule(causal, scale, dropout_p, block, res, g):
    q, k, v, kmask, seed, out, lse = res
    B, L, H, D = q.shape
    interpret = jax.default_backend() != "tpu"
    do = _to_bh(g)
    o = _to_bh(out)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    dq, dk, dv = _flash_bwd_pallas(
        _to_bh(q), _to_bh(k), _to_bh(v), kmask, seed, do, lse, delta,
        causal, scale, dropout_p, block, block, H, interpret)
    return (_from_bh(dq, B, H).astype(q.dtype),
            _from_bh(dk, B, H).astype(k.dtype),
            _from_bh(dv, B, H).astype(v.dtype),
            jnp.zeros_like(kmask), None)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal=False, scale=None, key_mask=None,
                    dropout_p=0.0, dropout_seed=None):
    """Public flash attention on raw arrays, (B,L,H,D).

    key_mask: optional additive mask over keys, shape (B, Lk) (or any shape
    reshapeable to it, e.g. the BERT (B,1,1,Lk) padding mask).  dropout_p
    applies to attention probabilities; dropout_seed (uint32 scalar) selects
    the deterministic in-kernel keep-mask.

    Limitation: key_mask is treated as a constant — its cotangent on the
    Pallas path is zero.  Do not feed a *learned* additive bias through
    key_mask; use dense_attention(mask=...) for differentiable biases.
    """
    B, L, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    if dropout_p > 0.0 and dropout_seed is None:
        # a silent default seed would replay one fixed keep-mask every step
        # (and the dense fallback would apply no dropout at all)
        raise ValueError("dropout_p > 0 requires dropout_seed (vary it per "
                         "step, e.g. jax.random.bits(key, (), jnp.uint32))")
    # choose the largest block size that tiles L exactly; overridable for
    # per-chip tuning (PADDLE_TPU_FLASH_BLOCK=256 etc.)
    import os as _os
    override = int(_os.environ.get("PADDLE_TPU_FLASH_BLOCK", "0"))
    if override and L % override == 0:
        block = override
    else:
        block = next((b for b in (512, 256, 128) if L % b == 0), None)
    if _use_pallas() and block is not None and q.shape == k.shape:
        kmask = (jnp.zeros((B, L), jnp.float32) if key_mask is None
                 else key_mask.reshape(B, L).astype(jnp.float32))
        seed = (jnp.zeros((1,), jnp.uint32) if dropout_seed is None
                else jnp.asarray(dropout_seed, jnp.uint32).reshape(1))
        return _flash_attention(q, k, v, kmask, seed, causal, scale,
                                float(dropout_p), block)
    mask4 = None if key_mask is None else \
        key_mask.reshape(B, 1, 1, k.shape[1]).astype(jnp.float32)
    dkey = None
    if dropout_p > 0.0 and dropout_seed is not None:
        dkey = jax.random.PRNGKey(jnp.asarray(dropout_seed, jnp.uint32).reshape(()))
    return dense_attention(q, k, v, mask=mask4, causal=causal, scale=scale,
                           dropout_p=dropout_p, dropout_key=dkey)


def _is_key_padding_mask(m, B, Lk) -> bool:
    """True for masks that broadcast over heads and query rows: (B,1,1,Lk),
    (1,1,1,Lk) or (B,1,Lk).  A 2-D (B,Lk) mask is deliberately NOT accepted:
    it is ambiguous with a (Lq,Lk) positional mask when B == Lq, which dense
    attention broadcasts over batch — different semantics."""
    if m is None:
        return False
    shape = tuple(m.shape)
    return shape in ((B, 1, 1, Lk), (1, 1, 1, Lk), (B, 1, Lk))


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Tensor-level entry (BLHD), used by nn.MultiHeadAttention / F.sdpa."""
    from ..core import rng
    B, Lk = key.shape[0], key.shape[1]
    raw_mask = getattr(attn_mask, "_data", attn_mask)
    dropout_key = None
    p = dropout_p if training else 0.0
    if p > 0.0:
        dropout_key = rng.next_key()

    if raw_mask is None or _is_key_padding_mask(raw_mask, B, Lk):
        def f(q, k, v, m, dk):
            seed = None if dk is None else \
                jax.random.bits(dk, (), jnp.uint32)
            km = None if m is None else jnp.broadcast_to(
                m.astype(jnp.float32).reshape(m.shape[0], Lk), (B, Lk))
            return flash_attention(q, k, v, causal=is_causal, key_mask=km,
                                   dropout_p=p, dropout_seed=seed)
        return apply(f, query, key, value, attn_mask,
                     None if dropout_key is None else Tensor(dropout_key))

    def f(q, k, v, m, dk):
        return dense_attention(q, k, v, mask=m, causal=is_causal,
                               dropout_p=p if dk is not None else 0.0,
                               dropout_key=dk)
    return apply(f, query, key, value, attn_mask,
                 None if dropout_key is None else Tensor(dropout_key))
