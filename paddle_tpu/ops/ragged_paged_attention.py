"""Ragged paged attention: ONE Pallas kernel over a flattened mixed batch
of prefill chunks and decode rows (arxiv 2604.15464, PAPERS.md), walking
each sequence's block table in-kernel via scalar prefetch.

The paged decode kernel (ops/paged_attention.py) issues exactly one query
per slot, so prefill and decode tokens can never share a device program —
every prompt bucket compiles its own prefill family and the engine pays a
separate decode tick.  This kernel removes the split: the query batch is a
flattened ``(total_q, nh, hd)`` ragged pack where sequence ``s`` owns rows
``[cu_q_lens[s], cu_q_lens[s+1])`` at kv positions
``[kv_lens[s] - q_len[s], kv_lens[s])`` — a decode row is just a sequence
with ``q_len == 1`` and a prefill chunk one with ``q_len == n``.  Causality
is per ROW (query at kv position p attends positions <= p), so any mixture
of admission prefill and in-flight decode runs as one program.

A speculative VERIFY chunk is the same shape by construction: a slot's
``[prev, d_0..d_{K-1}]`` rows at kv positions ``[t, t+K]`` are a
``q_len == K+1`` sequence — each draft row attends its predecessors'
freshly scattered k/v under the per-row causal rule, so both the kernel
and the gather fallback are verify-aware with no extra code path (the
ragged spec engine's fused draft+verify tick rides exactly this).

int8 ``(values, scales)`` pools (models/_decode.py quantize_kv layout) are
supported IN-KERNEL: the scale plane rides its own block spec and the
dequantize multiply fuses into the k/v read — no fp copy of the pool ever
materializes (the gather fallback's dequant transient disappears).

Grid is (total_q, table columns); the k/v BlockSpec index maps read the
prefetched table — ``table[row_seq[i], j]`` selects which physical pool
block the next DMA fetches, clamped to the row's last in-range column so
skipped steps cost neither DMA nor compute (the ops/paged_attention.py
discipline, generalized from one-row-per-slot to one-row-per-token).

Gated like every Pallas kernel here: real Mosaic lowering on TPU via
FLAGS_use_pallas_kernels, ``interpret=True`` for CPU CI
(FLAGS_paged_attn_interpret), with ``ragged_attention_ref`` as the XLA
gather fallback/oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def ragged_rows(cu_q_lens, kv_lens, total_q: int):
    """Expand the per-sequence ragged metadata into per-ROW metadata.

    cu_q_lens (S+1,) int32 nondecreasing with cu_q_lens[0] == 0: sequence
    ``s`` owns rows [cu_q_lens[s], cu_q_lens[s+1]) of the flattened pack
    (q_len == 0 sequences own no rows).  kv_lens (S,) int32: kv extent of
    each sequence AFTER this step's writes — its rows sit at kv positions
    [kv_lens[s] - q_len[s], kv_lens[s]).

    Returns (row_seq, row_pos), both (total_q,) int32: the owning sequence
    (clamped to [0, S)) and the kv position of every row; padding rows
    beyond cu_q_lens[S] get row_pos == -1 (the kernel and fallback mask
    them to garbage-but-finite output).
    """
    cu = jnp.asarray(cu_q_lens, jnp.int32)
    kv = jnp.asarray(kv_lens, jnp.int32)
    S = kv.shape[0]
    rows = jnp.arange(total_q, dtype=jnp.int32)
    seq = jnp.searchsorted(cu[1:], rows, side="right").astype(jnp.int32)
    valid = seq < S
    seq_c = jnp.minimum(seq, S - 1)
    q_len = jnp.diff(cu)
    pos = kv[seq_c] - q_len[seq_c] + (rows - cu[seq_c])
    return seq_c, jnp.where(valid, pos, jnp.int32(-1))


def _ragged_kernel(table_ref, seq_ref, pos_ref, pad_ref, q_ref, *rest,
                   bs, n_cols, scale, quantized):
    from jax.experimental import pallas as pl

    if quantized:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = rest
        ks_ref = vs_ref = None

    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def body():
        q = q_ref[0].astype(jnp.float32) * scale       # (nh, hd)
        k = k_ref[0].astype(jnp.float32)               # (bs, nh, hd)
        v = v_ref[0].astype(jnp.float32)
        if quantized:                                  # fused dequant
            k = k * ks_ref[0].astype(jnp.float32)[..., None]
            v = v * vs_ref[0].astype(jnp.float32)[..., None]
        # scores (nh, bs): contract hd, batch over heads
        sc = lax.dot_general(q, k, (((1,), (2,)), ((0,), (1,))),
                             preferred_element_type=jnp.float32)
        pos = j * bs + lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        valid = (pos <= pos_ref[i]) & (pos >= pad_ref[seq_ref[i]])
        sc = jnp.where(valid, sc, _NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
        p = jnp.exp(sc - m_new)                        # (nh, bs)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
        # (nh, hd): contract positions, batch over heads
        acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    # columns past the row's kv position: the clamped index map re-fetches
    # the row's last in-range block, which Pallas does not re-DMA, and
    # pl.when skips the FLOPs — padding rows (pos == -1) skip every column
    @pl.when(j * bs <= pos_ref[i])
    def _run():
        body()

    @pl.when(j == n_cols - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def ragged_attention_rows(q, pool_k, pool_v, table, row_seq, row_pos,
                          pad_lens=None, *, interpret=False):
    """Row-metadata entry point (the engine packs rows directly).

    q (T, nh, hd); pool_k/pool_v (NB+1, bs, nh, hd) — or int8
    ``(values, scales)`` pairs with scales (NB+1, bs, nh); table (S, C)
    int32 (block 0 = trash); row_seq (T,) int32 in [0, S); row_pos (T,)
    int32 kv position per row, -1 for padding rows; pad_lens (S,) int32
    left-pad masks (positions < pad masked), or None.

    Returns (T, nh, hd) in q's dtype; each row's output is attention over
    its sequence's pool positions [pad, row_pos] (garbage-but-finite
    zeros for padding rows).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, nh, hd = q.shape
    quantized = isinstance(pool_k, tuple)
    vals_k = pool_k[0] if quantized else pool_k
    NB1, bs = vals_k.shape[:2]
    S, C = table.shape
    if pad_lens is None:
        pad_lens = jnp.zeros((S,), jnp.int32)
    scale = 1.0 / (hd ** 0.5)

    kernel = functools.partial(_ragged_kernel, bs=bs, n_cols=C, scale=scale,
                               quantized=quantized)

    def kv_map(i, j, tb, rs, rp, pp):
        # clamp to the row's deepest in-range column; padding rows (-1)
        # map to the trash block
        col = jnp.minimum(j, jnp.maximum(rp[i], 0) // bs)
        return (jnp.where(rp[i] < 0, 0, tb[rs[i], col]), 0, 0, 0)

    def kv_scale_map(i, j, tb, rs, rp, pp):
        return kv_map(i, j, tb, rs, rp, pp)[:3]

    val_spec = pl.BlockSpec((1, bs, nh, hd), kv_map)
    scale_spec = pl.BlockSpec((1, bs, nh), kv_scale_map)
    in_specs = [pl.BlockSpec((1, nh, hd), lambda i, j, tb, rs, rp, pp:
                             (i, 0, 0))]
    operands = [q]
    for pool in (pool_k, pool_v):
        if quantized:
            in_specs += [val_spec, scale_spec]
            operands += [pool[0], pool[1]]
        else:
            in_specs.append(val_spec)
            operands.append(pool)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,                   # table, row_seq, row_pos, pad
        grid=(T, C),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nh, hd),
                               lambda i, j, tb, rs, rp, pp: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, hd), jnp.float32),
            pltpu.VMEM((nh, 1), jnp.float32),
            pltpu.VMEM((nh, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, nh, hd), q.dtype),
        interpret=interpret,
    )(table.astype(jnp.int32), jnp.asarray(row_seq, jnp.int32),
      jnp.asarray(row_pos, jnp.int32), jnp.asarray(pad_lens, jnp.int32),
      *operands)


def ragged_attention_ref(q, pool_k, pool_v, table, row_seq, row_pos,
                         pad_lens=None):
    """XLA fallback/oracle: densify each row's table-selected blocks and
    reuse cached_attention's kq=1 per-row form — EXACTLY the numerics of
    the paged engine's gather path, so kernel parity tests pin against
    the same oracle the serving engine is locked to.  int8 pools
    dequantize after the gather (only selected blocks pay the convert)."""
    from ..models._decode import cached_attention, dequantize_cache

    S, C = table.shape
    if pad_lens is None:
        pad_lens = jnp.zeros((S,), jnp.int32)
    seq = jnp.clip(jnp.asarray(row_seq, jnp.int32), 0, S - 1)

    def dense(pool):
        picked = jax.tree.map(lambda p: p[table], pool)   # (S, C, bs, …)
        g = dequantize_cache(picked, q.dtype)
        g = g.reshape((S, C * g.shape[2]) + g.shape[3:])
        return g[seq]                                     # (T, C·bs, nh, hd)

    out = cached_attention(q[:, None], dense(pool_k), dense(pool_v),
                           jnp.asarray(row_pos, jnp.int32),
                           pad_lens=pad_lens[seq])
    return out[:, 0]


def ragged_paged_attention(q, pool_k, pool_v, table, cu_q_lens, kv_lens,
                           pad_lens=None, *, interpret=False):
    """Ragged paged attention over per-SEQUENCE metadata (the PAPERS.md
    kernel interface): q (T, nh, hd) flattened mixed batch, cu_q_lens
    (S+1,) cumulative query lengths, kv_lens (S,) post-write kv extents,
    ``table`` (S, C) block tables into the (NB+1, bs, nh, hd) pools
    (int8 ``(values, scales)`` pairs supported — dequant fused into the
    in-kernel gather).  Rows past cu_q_lens[S] are padding.  See
    ragged_attention_rows for the row-level contract."""
    row_seq, row_pos = ragged_rows(cu_q_lens, kv_lens, q.shape[0])
    return ragged_attention_rows(q, pool_k, pool_v, table, row_seq,
                                 row_pos, pad_lens, interpret=interpret)
